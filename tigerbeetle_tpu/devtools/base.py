"""vet infrastructure: passes, violations, and closed JSON baselines.

The reference treats static analysis as part of the build (reference:
src/tidy.zig, src/copyhound.zig — discipline violations are build
failures, not review comments). `scripts/vet.py` is the driver; this
module is the shared machinery every pass builds on:

- `SourceFile`: one parsed source file (text + AST + per-line comments).
- `VetPass`: a named pass with documented checks; `run()` returns
  `Violation`s. Passes never print — the driver owns presentation.
- closed baselines: a pass may carry a JSON baseline of deliberate,
  explained sites. The baseline is CLOSED in both directions — a new
  site fails the run, and a baselined site that no longer exists fails
  too (the old open-set copyhound check let entries rot). Every entry
  carries a mandatory human `why` string; an empty `why` fails the run
  (`--update` writes new entries with an empty `why` precisely so the
  run stays red until a human justifies them).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import pathlib
import re


@dataclasses.dataclass
class Violation:
    file: str  # repo-relative path
    line: int
    pass_name: str
    check: str  # check id within the pass (see VetPass.checks)
    message: str
    # stable baseline key ("" = never baselinable: always a hard failure)
    site: str = ""

    def render(self) -> str:
        return (
            f"{self.file}:{self.line}: [{self.pass_name}/{self.check}] "
            f"{self.message}"
        )


class SourceFile:
    """One source file: text, lines, lazily parsed AST, and the `# noqa`
    / `# vet:` comment maps the passes share."""

    def __init__(self, rel: str, text: str):
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self._tree: ast.AST | None = None
        self._parse_error: SyntaxError | None = None
        self._parsed = False

    @property
    def tree(self) -> ast.AST | None:
        if not self._parsed:
            self._parsed = True
            try:
                self._tree = ast.parse(self.text)
            except SyntaxError as e:
                self._parse_error = e
        return self._tree

    @property
    def parse_error(self) -> SyntaxError | None:
        _ = self.tree  # force the lazy parse
        return self._parse_error

    # the lookbehind skips prose MENTIONS of noqa: documentation quotes
    # the marker in backticks (`# noqa`), real suppressions never do
    _NOQA_RE = re.compile(r"(?<!`)#\s*noqa(?::\s*([A-Za-z0-9_,\s-]+))?")

    def noqa(self) -> dict[int, set[str] | None]:
        """line -> named checks suppressed there, or None for a BARE
        `# noqa` (which tidy reports as its own violation)."""
        out: dict[int, set[str] | None] = {}
        for i, line in enumerate(self.lines, 1):
            m = self._NOQA_RE.search(line)
            if m is None:
                continue
            names = m.group(1)
            if names is None:
                out[i] = None
            else:
                out[i] = {
                    n.strip() for n in names.split(",") if n.strip()
                }
        return out

    _VET_RE = re.compile(r"#\s*vet:\s*(.+?)\s*$")

    def vet_comments(self) -> dict[int, str]:
        """line -> raw `# vet:` declaration text on that line."""
        out: dict[int, str] = {}
        for i, line in enumerate(self.lines, 1):
            m = self._VET_RE.search(line)
            if m is not None:
                out[i] = m.group(1)
        return out


def load_files(root: pathlib.Path, rels: list[str]) -> list[SourceFile]:
    return [
        SourceFile(rel, (root / rel).read_text()) for rel in sorted(rels)
    ]


def discover(root: pathlib.Path) -> list[str]:
    """Repo-relative paths of every Python source the passes scan."""
    rels: list[str] = []
    for base in ("tigerbeetle_tpu", "tests", "scripts"):
        for path in sorted((root / base).rglob("*.py")):
            rels.append(str(path.relative_to(root)))
    for extra in ("bench.py", "__graft_entry__.py"):
        if (root / extra).exists():
            rels.append(extra)
    return rels


class VetPass:
    """Base pass. Subclasses set `name`, `checks` (check id -> one-line
    explanation for --explain) and implement run()."""

    name = "base"
    doc = ""
    checks: dict[str, str] = {}
    baseline_name: str | None = None  # file name under scripts/, if any

    def run(self, files: list[SourceFile], config) -> list[Violation]:
        raise NotImplementedError


# ----------------------------------------------------------------------
# closed baselines
# ----------------------------------------------------------------------

BASELINE_VERSION = 2


def load_baseline(path: pathlib.Path) -> dict[str, dict]:
    """site -> {count, why}. Missing file = empty baseline."""
    if not path.exists():
        return {}
    raw = json.loads(path.read_text())
    if "version" not in raw:
        # v1 (open-set, why-less) schema: {rel: {kind: count}} — lift it
        # so --update can carry counts; every entry still needs a why
        # before the run goes green
        return {
            f"{rel}::{kind}": {
                "site": f"{rel}::{kind}", "count": n, "why": "",
            }
            for rel, kinds in raw.items()
            for kind, n in kinds.items()
        }
    assert raw.get("version") == BASELINE_VERSION, (
        f"{path.name}: expected baseline version {BASELINE_VERSION} "
        f"(run scripts/vet.py --update to migrate)"
    )
    return {e["site"]: e for e in raw["entries"]}


def save_baseline(path: pathlib.Path, sites: dict[str, int],
                  old: dict[str, dict]) -> int:
    """Write the v2 baseline for the observed `site -> count` map,
    carrying over existing `why` strings. Returns the number of entries
    left with an empty why (the run stays red until a human fills them).
    """
    entries = []
    unexplained = 0
    for site in sorted(sites):
        why = old.get(site, {}).get("why", "")
        if not why:
            unexplained += 1
        entries.append({"site": site, "count": sites[site], "why": why})
    path.write_text(
        json.dumps(
            {"version": BASELINE_VERSION, "entries": entries},
            indent=1,
            sort_keys=True,
        )
        + "\n"
    )
    return unexplained


def apply_baseline(
    pass_name: str,
    violations: list[Violation],
    baseline: dict[str, dict],
    baseline_file: str,
) -> list[Violation]:
    """Filter `violations` through a closed baseline.

    - a site whose count matches its entry is suppressed;
    - a count above the entry reports the excess as NEW sites;
    - a count below the entry (or a site gone entirely) reports the
      entry as STALE — the baseline must shrink with the code;
    - an entry with an empty `why` always fails."""
    out: list[Violation] = []
    counts: dict[str, list[Violation]] = {}
    for v in violations:
        if v.site:
            counts.setdefault(v.site, []).append(v)
        else:
            out.append(v)
    for site, vs in sorted(counts.items()):
        entry = baseline.get(site)
        if entry is None:
            out.extend(vs)
            continue
        if not entry.get("why"):
            out.append(
                Violation(
                    baseline_file, 0, pass_name, "baseline-why",
                    f"baseline entry {site!r} has no `why` — every "
                    "deliberate site needs a human justification",
                )
            )
        if len(vs) > entry["count"]:
            for v in vs[entry["count"]:]:
                v.message += (
                    f" ({len(vs)} sites vs {entry['count']} baselined)"
                )
                out.append(v)
        elif len(vs) < entry["count"]:
            out.append(
                Violation(
                    baseline_file, 0, pass_name, "baseline-stale",
                    f"baseline entry {site!r} expects {entry['count']} "
                    f"site(s) but only {len(vs)} exist — re-baseline "
                    "with --update (the baseline is closed)",
                )
            )
    for site, entry in sorted(baseline.items()):
        if site not in counts:
            out.append(
                Violation(
                    baseline_file, 0, pass_name, "baseline-stale",
                    f"baseline entry {site!r} matches nothing — the "
                    "site was removed; re-baseline with --update",
                )
            )
    return out


# ----------------------------------------------------------------------
# small AST helpers shared by passes
# ----------------------------------------------------------------------


def dotted(node: ast.AST) -> str | None:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def self_attr(node: ast.AST) -> str | None:
    """'x' when node is exactly `self.x`."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None
