"""tidy: source-form lint (the reference's src/tidy.zig analog).

Checks every Python source in the repo:
- no tabs, no trailing whitespace, lines <= 100 columns;
- no unused imports (AST-verified);
- `print()` only in user-facing surfaces (CLI/REPL/scripts/bench) —
  library code logs or returns, it does not print;
- `# noqa` must NAME the check it suppresses (`# noqa: unused-import`).
  A bare `# noqa` is itself a violation: an unlabeled suppression hides
  which rule it was meant to silence and survives the rule's removal.

noqa names: this pass's own check ids suppress the matching check;
flake8-style codes are accepted as names (so sources stay compatible
with external linters) and `F401` aliases `unused-import`.
"""

from __future__ import annotations

import ast

from tigerbeetle_tpu.devtools.base import SourceFile, VetPass, Violation

NOQA_ALIASES = {"F401": "unused-import"}


def _used_names(tree: ast.AST) -> set[str]:
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            out.add(node.id)
        elif isinstance(node, ast.Attribute):
            n: ast.AST = node
            while isinstance(n, ast.Attribute):
                n = n.value
            if isinstance(n, ast.Name):
                out.add(n.id)
    return out


class TidyPass(VetPass):
    name = "tidy"
    doc = __doc__
    checks = {
        "tab": "tab characters are banned (spaces only)",
        "trailing-whitespace": "no trailing whitespace",
        "line-length": "lines must fit in 100 columns",
        "unused-import": "imports must be used (or `# noqa: "
                         "unused-import` with a reason)",
        "library-print": "print() only in CLI/REPL/scripts/bench "
                         "surfaces; library code logs or returns",
        "bare-noqa": "`# noqa` must name the check it suppresses",
        "syntax": "every scanned source must parse",
    }

    def run(self, files: list[SourceFile], config) -> list[Violation]:
        out: list[Violation] = []
        for f in files:
            out.extend(self._check(f, config))
        return out

    def _suppressed(self, noqa, line: int, check: str) -> bool:
        names = noqa.get(line)
        if not names:  # absent, or bare (bare suppresses nothing)
            return False
        names = {NOQA_ALIASES.get(n, n) for n in names}
        return check in names

    def _check(self, f: SourceFile, config) -> list[Violation]:
        out: list[Violation] = []

        def emit(line: int, check: str, message: str) -> None:
            out.append(Violation(f.rel, line, self.name, check, message))

        exempt_len = f.rel in config.line_max_exempt
        for i, line in enumerate(f.lines, 1):
            if "\t" in line:
                emit(i, "tab", "tab character")
            if line != line.rstrip():
                emit(i, "trailing-whitespace", "trailing whitespace")
            if len(line) > config.line_max and not exempt_len:
                emit(
                    i, "line-length",
                    f"line exceeds {config.line_max} columns",
                )
        if f.parse_error is not None:
            emit(
                f.parse_error.lineno or 0, "syntax",
                f"syntax error: {f.parse_error.msg}",
            )
            return out
        noqa = f.noqa()
        for i, names in sorted(noqa.items()):
            if names is None:
                emit(
                    i, "bare-noqa",
                    "bare `# noqa` — name the check it suppresses "
                    "(e.g. `# noqa: unused-import`)",
                )
        tree = f.tree
        used = _used_names(tree)
        in_init = f.rel.endswith("__init__.py")
        for node in ast.walk(tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)) and not in_init:
                if (
                    isinstance(node, ast.ImportFrom)
                    and node.module == "__future__"
                ):
                    continue
                if self._suppressed(noqa, node.lineno, "unused-import"):
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    name = (alias.asname or alias.name).split(".")[0]
                    if name not in used:
                        emit(
                            node.lineno, "unused-import",
                            f"unused import {name!r}",
                        )
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
                and f.rel.startswith("tigerbeetle_tpu/")
                and f.rel not in config.print_ok
                and not self._suppressed(noqa, node.lineno, "library-print")
            ):
                emit(node.lineno, "library-print", "print() in library code")
        return out
