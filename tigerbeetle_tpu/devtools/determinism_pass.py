"""determinism: sim-reachable code must stay seed-deterministic.

The VOPR's whole value rests on one property: same seed -> byte-identical
runs (state checker digests, sim trace dumps, shrinker reproductions).
PR 3/4/6 each re-proved it by hand after touching the pipeline; this
pass machine-checks the sources of nondeterminism instead.

Scope: the sim-reachable module set — the static import closure of
`testing/simulator.py` and `scripts/vopr.py` over the package, minus the
explicit prod-only allowlist in the config (modules the closure touches
via imports but that only prod composition roots construct — each
allowlist entry carries its reason). Within scope:

- wall clocks (`time.time` / `monotonic` / `perf_counter` / `*_ns` /
  `sleep`) are forbidden outside the clock seam (io/time.py) — sim time
  comes from DeterministicTime ticks            [check: wall-clock]
- unseeded randomness: module-level `random.*` calls, `random.Random()`
  with no seed argument, `os.urandom`, `uuid.uuid4`
                                               [check: unseeded-random]
- iteration over a `set` (ids have no stable order; wrap in `sorted()`)
  — detected for locals/attributes assigned from set literals/calls or
  annotated `set[...]`                         [check: set-iteration]
- direct `threading.Thread` / `ThreadPoolExecutor` construction outside
  the executor seam modules (the ThreadedSpillIO/DeferredSpillIO seam
  and the WAL writer pool) — thread timing must never reach sim state
                                               [check: direct-thread]

Deliberate sites (timing that feeds observability only, latency
modeling, prod-gated threads) live in the closed baseline
(scripts/determinism_baseline.json), each with a mandatory `why`.
"""

from __future__ import annotations

import ast

from tigerbeetle_tpu.devtools.base import (
    SourceFile,
    VetPass,
    Violation,
    dotted,
)

WALL_CLOCK_FNS = {
    "time", "monotonic", "perf_counter", "time_ns", "monotonic_ns",
    "perf_counter_ns", "sleep",
}


def _module_of(rel: str) -> str | None:
    """'tigerbeetle_tpu/vsr/journal.py' -> 'tigerbeetle_tpu.vsr.journal'"""
    if not rel.endswith(".py"):
        return None
    mod = rel[:-3].replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def sim_closure(files: list[SourceFile], roots: list[str]) -> set[str]:
    """Repo-relative paths of the package modules statically reachable
    from the roots (imports anywhere in the file, including nested
    function-level imports). Importing a name from a package pulls in
    both the package __init__ and, when the name is itself a submodule,
    that submodule."""
    by_mod: dict[str, SourceFile] = {}
    for f in files:
        mod = _module_of(f.rel)
        if mod is not None:
            by_mod[mod] = f

    def imports_of(f: SourceFile) -> set[str]:
        # candidate dotted names; expanded to scanned modules below
        raw: set[str] = set()
        if f.tree is None:
            return raw
        mod = _module_of(f.rel)
        is_pkg = f.rel.endswith("/__init__.py")
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    raw.add(alias.name)
            elif isinstance(node, ast.ImportFrom):
                base = node.module
                if node.level > 0:
                    # relative import: resolve against this module's
                    # package (a package __init__'s first level is the
                    # package itself)
                    if mod is None:
                        continue
                    parts = mod.split(".")
                    drop = node.level - 1 if is_pkg else node.level
                    if drop >= len(parts):
                        continue  # escapes the scanned tree
                    pkg = parts[: len(parts) - drop]
                    base = ".".join(pkg + ([base] if base else []))
                if base is None:
                    continue
                raw.add(base)
                for alias in node.names:
                    raw.add(f"{base}.{alias.name}")
        # importing a.b.c executes a/__init__ and a.b/__init__ too —
        # every ancestor package in the file set is part of the closure
        out: set[str] = set()
        for name in raw:
            parts = name.split(".")
            for i in range(1, len(parts) + 1):
                prefix = ".".join(parts[:i])
                if prefix in by_mod:
                    out.add(prefix)
        return out

    seen: set[str] = set()
    queue: list[SourceFile] = [f for f in files if f.rel in roots]
    # the roots themselves are in scope — the closure ANCHORS on them,
    # it does not exempt them (vopr.py drawing from an unseeded RNG
    # would defeat the lint as surely as any module it imports)
    reached: set[str] = {f.rel for f in queue}
    while queue:
        f = queue.pop()
        if f.rel in seen:
            continue
        seen.add(f.rel)
        for mod in imports_of(f):
            tgt = by_mod[mod]
            reached.add(tgt.rel)
            if tgt.rel not in seen:
                queue.append(tgt)
    return reached


class _SetTypes(ast.NodeVisitor):
    """Names/attributes assigned from set expressions (one level)."""

    def __init__(self):
        self.set_names: set[str] = set()  # 'x' or 'self.x'

    def _target_key(self, node: ast.AST) -> str | None:
        if isinstance(node, ast.Name):
            return node.id
        d = dotted(node)
        return d

    def _is_set_expr(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Set):
            return True
        if isinstance(node, ast.SetComp):
            return True
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            return d == "set"
        return False

    def _is_set_ann(self, ann: ast.AST) -> bool:
        if isinstance(ann, ast.Subscript):
            ann = ann.value
        if isinstance(ann, ast.Name):
            return ann.id == "set"
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            return ann.value.startswith("set")
        return False

    def visit_Assign(self, node: ast.Assign) -> None:
        if self._is_set_expr(node.value):
            for t in node.targets:
                key = self._target_key(t)
                if key:
                    self.set_names.add(key)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if self._is_set_ann(node.annotation) or (
            node.value is not None and self._is_set_expr(node.value)
        ):
            key = self._target_key(node.target)
            if key:
                self.set_names.add(key)
        self.generic_visit(node)

    # nested defs are their own scope — walked separately, so a local
    # set in one function cannot shadow-type a like-named local in
    # another (self.* attribute keys are merged file-wide by the caller)
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass

    visit_AsyncFunctionDef = visit_FunctionDef


class DeterminismPass(VetPass):
    name = "determinism"
    doc = __doc__
    baseline_name = "determinism_baseline.json"
    checks = {
        "wall-clock": "wall-clock call outside the io/time.py clock "
                      "seam in sim-reachable code",
        "unseeded-random": "unseeded randomness (random module fns, "
                           "Random(), os.urandom, uuid4)",
        "set-iteration": "iteration over a set — wrap in sorted() for "
                         "a stable order",
        "direct-thread": "Thread/ThreadPoolExecutor outside the "
                         "executor seam modules",
    }

    def run(self, files: list[SourceFile], config) -> list[Violation]:
        closure = sim_closure(files, config.sim_roots)
        out: list[Violation] = []
        for f in files:
            if f.rel not in closure:
                continue
            if f.rel in config.prod_only:
                continue
            if f.rel in config.clock_seam:
                continue
            if f.tree is None:
                continue
            out.extend(self._check(f, config))
        return out

    def _check(self, f: SourceFile, config) -> list[Violation]:
        out: list[Violation] = []
        in_seam = f.rel in config.executor_seam
        # aliases of the `time` module in this file (import time as
        # _t) — seeded only by an actual import, so a parameter named
        # `time` carrying the DeterministicTime clock seam (the natural
        # name for it) is not misread as the stdlib module
        time_aliases: set[str] = set()
        random_aliases: set[str] = set()
        # bare names bound by from-imports (`from time import
        # perf_counter [as pc]`): local name -> original function
        clock_names: dict[str, str] = {}
        random_names: dict[str, str] = {}
        entropy_names: dict[str, str] = {}
        ENTROPY = {
            ("os", "urandom"), ("uuid", "uuid4"),
            ("secrets", "token_bytes"),
        }
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "time":
                        time_aliases.add(alias.asname or "time")
                    if alias.name == "random":
                        random_aliases.add(alias.asname or "random")
            elif isinstance(node, ast.ImportFrom) and node.level == 0:
                for alias in node.names:
                    local = alias.asname or alias.name
                    if node.module == "time" and alias.name in WALL_CLOCK_FNS:
                        clock_names[local] = alias.name
                    if node.module == "random":
                        random_names[local] = alias.name
                    if (node.module, alias.name) in ENTROPY:
                        entropy_names[local] = (
                            f"{node.module}.{alias.name}"
                        )
        # per-scope set-typed names: a local `x = set()` in one function
        # must not flag iteration over an unrelated `x` elsewhere;
        # `self.x`-style dotted keys are attributes and stay file-wide
        scopes: list[list] = [list(f.tree.body)]
        for node in ast.walk(f.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append(node.body)
        attr_set_names: set[str] = set()
        local_set_names: list[set[str]] = []
        for body in scopes:
            st = _SetTypes()
            for stmt in body:
                st.visit(stmt)
            attr_set_names |= {n for n in st.set_names if "." in n}
            local_set_names.append(
                {n for n in st.set_names if "." not in n}
            )

        def emit(line, check, msg, detail):
            out.append(
                Violation(
                    f.rel, line, self.name, check, msg,
                    site=f"{f.rel}::{check}::{detail}",
                )
            )

        for node in ast.walk(f.tree):
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                if d is not None:
                    parts = d.split(".")
                    if (
                        len(parts) == 2
                        and parts[0] in time_aliases
                        and parts[1] in WALL_CLOCK_FNS
                    ):
                        emit(
                            node.lineno, "wall-clock",
                            f"{d}() in sim-reachable code — route "
                            "through the Time seam (io/time.py) or "
                            "baseline with a why",
                            parts[1],
                        )
                    if (
                        len(parts) == 2
                        and parts[0] in random_aliases
                        and parts[1] != "Random"
                    ):
                        emit(
                            node.lineno, "unseeded-random",
                            f"{d}() uses the shared unseeded RNG — "
                            "thread a random.Random(seed) through",
                            parts[1],
                        )
                    if (
                        len(parts) == 2
                        and parts[0] in random_aliases
                        and parts[1] == "Random"
                        and not node.args
                        and not node.keywords
                    ):
                        emit(
                            node.lineno, "unseeded-random",
                            "random.Random() without a seed",
                            "Random",
                        )
                    if d in ("os.urandom", "uuid.uuid4", "secrets.token_bytes"):
                        emit(
                            node.lineno, "unseeded-random",
                            f"{d}() is entropy, not simulation",
                            parts[-1],
                        )
                    if len(parts) == 1:
                        name = parts[0]
                        if name in clock_names:
                            emit(
                                node.lineno, "wall-clock",
                                f"{name}() (from-import of "
                                f"time.{clock_names[name]}) in "
                                "sim-reachable code — route through "
                                "the Time seam (io/time.py) or "
                                "baseline with a why",
                                clock_names[name],
                            )
                        if name in random_names:
                            orig = random_names[name]
                            if orig != "Random":
                                emit(
                                    node.lineno, "unseeded-random",
                                    f"{name}() (from-import of "
                                    f"random.{orig}) uses the shared "
                                    "unseeded RNG — thread a "
                                    "random.Random(seed) through",
                                    orig,
                                )
                            elif not node.args and not node.keywords:
                                emit(
                                    node.lineno, "unseeded-random",
                                    "Random() without a seed",
                                    "Random",
                                )
                        if name in entropy_names:
                            emit(
                                node.lineno, "unseeded-random",
                                f"{name}() "
                                f"({entropy_names[name]}) is "
                                "entropy, not simulation",
                                name,
                            )
                    leaf = parts[-1]
                    if leaf in ("Thread", "ThreadPoolExecutor") and not in_seam:
                        emit(
                            node.lineno, "direct-thread",
                            f"{d}() in sim-reachable code bypasses the "
                            "spill/WAL executor seam — thread timing "
                            "must never reach sim state",
                            leaf,
                        )
        # for x in <set>: / comprehensions over a set — checked per
        # scope so one function's set local cannot taint another's
        def scope_walk(body):
            stack = list(body)
            while stack:
                n = stack.pop()
                yield n
                for c in ast.iter_child_nodes(n):
                    if not isinstance(
                        c, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        stack.append(c)

        for body, names in zip(scopes, local_set_names):
            in_scope = names | attr_set_names
            for node in scope_walk(body):
                iters: list[ast.AST] = []
                if isinstance(node, (ast.For, ast.AsyncFor)):
                    iters.append(node.iter)
                elif isinstance(
                    node, (ast.ListComp, ast.SetComp, ast.DictComp,
                           ast.GeneratorExp)
                ):
                    iters.extend(g.iter for g in node.generators)
                for it in iters:
                    key = (
                        dotted(it) if not isinstance(it, ast.Name)
                        else it.id
                    )
                    if key is not None and key in in_scope:
                        emit(
                            it.lineno, "set-iteration",
                            f"iteration over set `{key}` has no "
                            "stable order — wrap in sorted()",
                            key.split(".")[-1],
                        )
        return out
