"""races: thread-ownership lint over the codebase's thread seams.

The system has five deliberate thread seams (the WAL writer pool, the
spill IO executor, the device-shadow loop, the CDC pump, and the
ingress/bus event loop). Every recent PR hand-verified in review that
shared mutable state crossing those seams is lock- or handoff-protected;
this pass turns that review into CI.

Annotation vocabulary (a `# vet:` comment on the attribute's assignment
line, or on its own line directly above it):

- `# vet: owner=<thread>`      the attribute belongs to one thread;
                               every access from another thread fails.
- `# vet: guarded-by=<attr>`   writes must happen inside a lexical
                               `with self.<attr>:` scope (or a `with`
                               over a local derived from `self.<attr>`,
                               the per-sector-lock pattern). Lock-free
                               reads are allowed — the double-checked
                               registry pattern stays legal, at the
                               reader's own staleness risk.
- `# vet: handoff`             the attribute crosses threads through a
                               declared handoff discipline (queue,
                               fence, join-before-read); the pass
                               trusts the declaration.

For each class in the scanned seam modules the pass:

1. builds a per-attribute access map across every method body (nested
   functions included; `self.x = ...`, `self.x += ...`, `self.x[k] =
   ...` and mutating method calls like `self.x.append(...)` count as
   writes);
2. infers each method's executing thread from the seam entry points —
   `threading.Thread(target=self.m, name=...)`, executor
   `submit(self.m)` / `submit(nested_fn)` (including one level of
   submit-forwarder methods), and `add_done_callback` (callbacks run on
   the completing worker thread). Everything else runs on "main" (the
   event loop); `__init__` is construction and is exempt;
3. fails any attribute written from two threads — or written from one
   and read from another — without a `guarded-by` lock held at the
   writes, a matching `owner`, or a declared `handoff`.

Thread names: `main`, `thread:<name>` (or the literal Thread name),
`worker:<executor attr>`, `callback`. Config `thread_aliases` maps
human annotation names (e.g. `event-loop`) onto inferred names.
"""

from __future__ import annotations

import ast
import dataclasses

from tigerbeetle_tpu.devtools.base import (
    SourceFile,
    VetPass,
    Violation,
    dotted,
    self_attr,
)

# method names on `self.<attr>.<m>(...)` that mutate the attribute
MUTATORS = {
    "append", "appendleft", "extend", "insert", "remove", "discard",
    "pop", "popleft", "popitem", "clear", "update", "add", "set",
    "setdefault", "put", "put_nowait", "observe", "sort", "reverse",
    "write", "start_thread",
}

# method names that submit a callable onto another thread
SUBMITTERS = {"submit", "submit_io", "_io_submit", "_submit"}

MAIN = "main"
CALLBACK = "callback"


@dataclasses.dataclass
class Access:
    attr: str
    write: bool
    line: int
    locks: frozenset  # self-attrs whose locks are lexically held
    method: str       # qualified method name (for messages)


@dataclasses.dataclass
class _Method:
    qualname: str
    private: bool
    accesses: list
    calls: set          # self.<m>() call targets
    spawned: bool = False


class _MethodScan(ast.NodeVisitor):
    """One method (or nested function) body: accesses, calls, spawns."""

    def __init__(self, cls: "_ClassScan", qualname: str):
        self.cls = cls
        self.qualname = qualname
        self.accesses: list[Access] = []
        self.calls: set[str] = set()
        self.locks: list[str] = []      # with-stack of held lock attrs
        self.local_src: dict[str, set[str]] = {}  # local -> self attrs
        # Lambda nodes claimed as spawn args (their accesses were
        # recorded on the SPAWN thread; visit_Lambda must not re-record
        # them on the enclosing thread, where they never run)
        self.claimed_lambdas: set[int] = set()

    def _held(self) -> frozenset:
        return frozenset(self.locks)

    def _access(self, attr: str, write: bool, line: int) -> None:
        self.accesses.append(
            Access(attr, write, line, self._held(), self.qualname)
        )

    # -- expression-level read/write classification ---------------------

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = self_attr(node)
        if attr is not None:
            write = isinstance(node.ctx, (ast.Store, ast.Del))
            self._access(attr, write, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        attr = self_attr(node.target)
        if attr is not None:
            self._access(attr, True, node.lineno)
        sub = node.target
        if isinstance(sub, ast.Subscript):
            attr = self_attr(sub.value)
            if attr is not None:
                self._access(attr, True, node.lineno)
            # reads inside the index (`self.buf[self.head] += 1`)
            self.visit(sub.slice)
        # visit (not generic_visit): the RHS may BE a self-attribute
        # (`self.total += self.base`) — generic_visit would dispatch
        # only on its children and drop the read
        self.visit(node.value)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            attr = self_attr(node.value)
            if attr is not None:
                self._access(attr, True, node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        # self.<attr>.<mutator>(...) is a write to <attr>
        if isinstance(f, ast.Attribute):
            attr = self_attr(f.value)
            if attr is not None and f.attr in MUTATORS:
                self._access(attr, True, node.lineno)
            # self.<m>(...) is an intra-class call edge
            if attr is None and self_attr(f) is not None:
                self.calls.add(f.attr)
        # executor submit / thread spawn / callbacks — outside the
        # Attribute branch: `from threading import Thread` spawns with a
        # bare `Thread(...)` Name call
        self.cls.scan_spawn(self, node)
        self.generic_visit(node)

    def visit_With(self, node: ast.With) -> None:
        held = []
        for item in node.items:
            expr = item.context_expr
            # self_attr is None for calls (`with self.hist.time():`) —
            # only a bare `self.<attr>` or a lock-derived local counts
            attr = self_attr(expr)
            if attr is None and isinstance(expr, ast.Name):
                held.extend(self.local_src.get(expr.id, ()))
            elif attr is not None:
                held.append(attr)
            # visiting the context expr still records its read
            self.visit(expr)
        self.locks.extend(held)
        for stmt in node.body:
            self.visit(stmt)
        for _ in held:
            self.locks.pop()

    def visit_Assign(self, node: ast.Assign) -> None:
        # track locals derived from self attrs (per-sector lock pattern:
        # `lock = self._sector_locks.setdefault(...)` -> `with lock:`)
        src_attrs = {
            self_attr(n)
            for n in ast.walk(node.value)
            if self_attr(n) is not None
        }
        for t in node.targets:
            if isinstance(t, ast.Name):
                self.local_src[t.id] = {a for a in src_attrs if a}
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # nested function: its own pseudo-method; thread decided by how
        # the enclosing body uses it (spawn args) or inherits the parent
        self.cls.add_nested(self, node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        if id(node) in self.claimed_lambdas:
            return  # runs on the spawn thread; recorded there already
        # dispatch on the body expression itself (generic_visit would
        # visit only its children, dropping e.g. a mutator Call at the
        # top level of `lambda f: self._pending.discard(f)`)
        self.visit(node.body)


class _ClassScan:
    def __init__(self, node: ast.ClassDef, forwarders: set[str],
                 thread_names: frozenset = frozenset()):
        self.node = node
        self.forwarders = forwarders
        # file-level names bound to threading.Thread (`from threading
        # import Thread [as T]`) — beyond the `.Thread` leaf heuristic
        self.thread_names = thread_names
        self.methods: dict[str, _Method] = {}
        # pseudo-method qualname -> entry threads from spawn points
        self.entries: dict[str, set[str]] = {}
        self.nested_parent: dict[str, str] = {}
        self._scan()

    # -- spawn-point recognition ----------------------------------------

    def _callable_target(self, arg: ast.AST) -> str | None:
        """'m' for self.m, '<local fn name>' for a bare name."""
        attr = self_attr(arg)
        if attr is not None:
            return attr
        if isinstance(arg, ast.Name):
            return arg.id
        # self.<attr>.<method> (e.g. self._pending.discard): a bound
        # method of an attribute — record as a callback ACCESS instead
        return None

    def note_spawn_args(self, scan: "_MethodScan", node: ast.Call,
                        thread: str) -> None:
        # only the FIRST positional arg is the callable — the rest are
        # data whose names must not be misread as spawn targets
        # (`submit(self._job, flush)` where `flush` is also a method)
        for arg in node.args[:1]:
            target = self._callable_target(arg)
            if target is not None:
                self.entries.setdefault(
                    scan.qualname.split(".")[0] + "." + target
                    if target not in self.node_method_names else target,
                    set(),
                ).add(thread)
            elif isinstance(arg, ast.Attribute):
                # bound method of an attribute: the call mutates/reads
                # that attribute on the spawn thread
                owner = self_attr(arg.value)
                if owner is not None:
                    scan.accesses.append(
                        Access(
                            owner, arg.attr in MUTATORS, node.lineno,
                            frozenset(), f"{thread}-callback",
                        )
                    )
            elif isinstance(arg, ast.Lambda):
                # inline callback: its body executes on the spawn
                # thread — scan it there, and mark it so the enclosing
                # method's walk does not also claim it for ITS thread
                sub = _MethodScan(self, f"{scan.qualname}.<lambda>")
                sub.local_src = dict(scan.local_src)
                sub.visit(arg.body)
                for a in sub.accesses:
                    scan.accesses.append(
                        Access(a.attr, a.write, a.line, a.locks,
                               f"{thread}-callback")
                    )
                scan.calls |= sub.calls
                scan.claimed_lambdas.add(id(arg))

    def scan_spawn(self, scan: "_MethodScan", node: ast.Call) -> None:
        d = dotted(node.func)
        # threading.Thread(target=self.m, name="x") — by dotted leaf, or
        # by a from-import binding (incl. aliased) collected per file
        if d is not None and (
            d.split(".")[-1] == "Thread" or d in self.thread_names
        ):
            target = None
            name = None
            for kw in node.keywords:
                if kw.arg == "target":
                    target = self._callable_target(kw.value)
                if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                    name = str(kw.value.value)
            if target is None:
                # positional target: threading.Thread(group, target,
                # ...) puts the callable second; Thread-like wrappers
                # often take it first
                for arg in node.args[1:2] + node.args[:1]:
                    target = self._callable_target(arg)
                    if target is not None:
                        break
            if target is not None:
                thread = name or f"thread:{target}"
                self.entries.setdefault(target, set()).add(thread)
            return
        if not isinstance(node.func, ast.Attribute):
            return
        meth = node.func.attr
        if meth in SUBMITTERS | self.forwarders:
            ex = self_attr(node.func.value)
            if ex is None and isinstance(node.func.value, ast.Name):
                if node.func.value.id == "self":
                    # self.<submit-forwarder>(fn): name the worker after
                    # the forwarder — one stable name per seam
                    ex = meth
                else:
                    srcs = scan.local_src.get(node.func.value.id, set())
                    ex = next(iter(sorted(srcs)), None)
            thread = f"worker:{ex}" if ex else "worker"
            self.note_spawn_args(scan, node, thread)
        elif meth == "add_done_callback":
            self.note_spawn_args(scan, node, CALLBACK)

    # -- scanning --------------------------------------------------------

    def add_nested(self, parent: "_MethodScan", node: ast.FunctionDef):
        qual = f"{parent.qualname}.{node.name}"
        scan = _MethodScan(self, qual)
        scan.local_src = dict(parent.local_src)
        for stmt in node.body:
            scan.visit(stmt)
        self.methods[qual] = _Method(
            qual, True, scan.accesses, scan.calls
        )
        self.nested_parent[qual] = parent.qualname
        # record the local name so spawn args can find it
        parent.local_src.setdefault(node.name, set())

    def _scan(self) -> None:
        self.node_method_names = {
            n.name
            for n in self.node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        for n in self.node.body:
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            scan = _MethodScan(self, n.name)
            for stmt in n.body:
                scan.visit(stmt)
            self.methods[n.name] = _Method(
                n.name, n.name.startswith("_"), scan.accesses, scan.calls
            )

    # -- thread propagation ----------------------------------------------

    def method_threads(self) -> dict[str, set[str]]:
        threads: dict[str, set[str]] = {
            q: set() for q in self.methods
        }
        spawned: set[str] = set()
        for target, ts in self.entries.items():
            if target.startswith("@"):
                continue
            # resolve: plain method name, or nested qualname suffix
            for q in self.methods:
                if q == target or q.endswith("." + target):
                    threads[q] |= ts
                    spawned.add(q)
        # non-spawned, non-nested methods are callable from the event
        # loop; nested ones inherit their parent (resolved below)
        for q in self.methods:
            if q in spawned:
                continue
            if q in self.nested_parent:
                continue  # inherits via call/parent propagation
            threads[q].add(MAIN)
        # nested, never-spawned functions run where their parent runs
        for q, parent in self.nested_parent.items():
            if q not in spawned:
                threads[q] |= threads.get(parent, {MAIN})
        # propagate along intra-class call edges to a fixed point
        changed = True
        while changed:
            changed = False
            for q, m in self.methods.items():
                for callee in m.calls:
                    for q2 in self.methods:
                        if q2 == callee or q2.endswith("." + callee):
                            if not threads[q] <= threads[q2]:
                                threads[q2] |= threads[q]
                                changed = True
            for q, parent in self.nested_parent.items():
                if q not in spawned and not threads[parent] <= threads[q]:
                    threads[q] |= threads[parent]
                    changed = True
        self.spawned = spawned
        return threads


def _parse_vet_decl(text: str) -> dict[str, str] | None:
    """'owner=x' / 'guarded-by=y' / 'handoff' -> key/value dict, or
    None when the declaration does not parse."""
    out: dict[str, str] = {}
    for token in text.replace(",", " ").split():
        if token == "handoff":
            out["handoff"] = "yes"
        elif "=" in token:
            k, v = token.split("=", 1)
            if k not in ("owner", "guarded-by") or not v:
                return None
            out[k] = v
        else:
            return None
    return out or None


class RacePass(VetPass):
    name = "races"
    doc = __doc__
    baseline_name = "races_baseline.json"
    checks = {
        "unannotated-shared": "attribute crosses threads with no "
                              "owner/guarded-by/handoff declaration",
        "owner": "attribute accessed off its declared owner thread",
        "guarded-by": "attribute written outside its declared lock",
        "bad-annotation": "malformed or unresolvable `# vet:` "
                          "declaration",
    }

    def run(self, files: list[SourceFile], config) -> list[Violation]:
        out: list[Violation] = []
        for f in files:
            if f.rel not in config.race_scan:
                continue
            if f.tree is None:
                continue
            decls, bad = self._decls(f)
            out.extend(bad)
            thread_names = self._thread_names(f)
            for node in f.tree.body:
                if isinstance(node, ast.ClassDef):
                    out.extend(
                        self._check_class(
                            f, node, decls, config, thread_names
                        )
                    )
        return out

    @staticmethod
    def _thread_names(f: SourceFile) -> frozenset:
        """Local names bound to threading.Thread by from-imports."""
        names = set()
        for node in ast.walk(f.tree):
            if isinstance(node, ast.ImportFrom) and node.module == "threading":
                for alias in node.names:
                    if alias.name == "Thread":
                        names.add(alias.asname or alias.name)
        return frozenset(names)

    # -- annotation collection -------------------------------------------

    def _decls(self, f: SourceFile):
        """(line -> decl dict) for every `# vet:` comment; malformed
        ones become violations."""
        decls: dict[int, dict[str, str]] = {}
        bad: list[Violation] = []
        for line, text in f.vet_comments().items():
            d = _parse_vet_decl(text)
            if d is None:
                bad.append(
                    Violation(
                        f.rel, line, self.name, "bad-annotation",
                        f"cannot parse `# vet: {text}` — expected "
                        "owner=<thread>, guarded-by=<attr>, or handoff",
                    )
                )
            else:
                decls[line] = d
        return decls, bad

    def _attr_decl(
        self, f, decls, assign_lines: dict[str, list[int]]
    ) -> tuple[dict[str, dict], list[Violation]]:
        """Attach each vet declaration to the attribute assigned on its
        line (or on the first assignment line directly below a
        standalone comment line)."""
        per_attr: dict[str, dict] = {}
        out: list[Violation] = []
        line_to_attr: dict[int, str] = {}
        for attr, lines in assign_lines.items():
            for ln in lines:
                line_to_attr.setdefault(ln, attr)
        for line, d in sorted(decls.items()):
            attr = line_to_attr.get(line)
            if attr is None:
                # standalone comment: applies to the next assignment
                # within the following 2 lines
                for probe in (line + 1, line + 2):
                    attr = line_to_attr.get(probe)
                    if attr is not None:
                        break
            if attr is None:
                continue  # not attached to this class's attrs
            prev = per_attr.get(attr)
            if prev is not None and prev != d:
                out.append(
                    Violation(
                        f.rel, line, self.name, "bad-annotation",
                        f"conflicting vet declarations for `{attr}`",
                    )
                )
            per_attr[attr] = d
        return per_attr, out

    # -- per-class check --------------------------------------------------

    def _check_class(self, f, node: ast.ClassDef, decls, config,
                     thread_names: frozenset = frozenset()):
        out: list[Violation] = []
        scan = _ClassScan(node, forwarders=set(config.submit_forwarders),
                          thread_names=thread_names)
        threads = scan.method_threads()
        aliases = config.thread_aliases

        # attribute universe + assignment lines (declaration sites)
        assign_lines: dict[str, list[int]] = {}
        for q, m in scan.methods.items():
            for a in m.accesses:
                if a.write:
                    assign_lines.setdefault(a.attr, []).append(a.line)
        for n in node.body:  # class-level declarations
            targets = []
            if isinstance(n, ast.Assign):
                targets = n.targets
            elif isinstance(n, ast.AnnAssign):
                targets = [n.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    assign_lines.setdefault(t.id, []).append(n.lineno)

        per_attr, bad = self._attr_decl(f, decls, assign_lines)
        out.extend(bad)

        # collect accesses per attribute with resolved threads;
        # __init__ is construction — exempt. A nested def under it is
        # exempt only while it stays un-spawned: `def loop(): ...;
        # Thread(target=loop)` in a constructor runs on the spawned
        # thread later, not at construction time
        acc: dict[str, list[tuple[str, Access]]] = {}
        for q, m in scan.methods.items():
            if q == "__init__" or (
                q.startswith("__init__.") and q not in scan.spawned
            ):
                continue
            for a in m.accesses:
                ts = threads.get(q) or {MAIN}
                if a.method.endswith("-callback"):
                    ts = {a.method.rsplit("-", 1)[0]}
                for t in ts:
                    acc.setdefault(a.attr, []).append((t, a))

        for attr, pairs in sorted(acc.items()):
            decl = per_attr.get(attr, {})
            if "handoff" in decl:
                continue
            write_threads = {t for t, a in pairs if a.write}
            all_threads = {t for t, a in pairs}
            if "guarded-by" in decl:
                lock = decl["guarded-by"]
                if lock not in assign_lines:
                    out.append(
                        Violation(
                            f.rel, min(assign_lines.get(attr, [0])),
                            self.name, "bad-annotation",
                            f"`{attr}` guarded-by `{lock}` but no such "
                            "attribute exists on the class",
                        )
                    )
                    continue
                for t, a in pairs:
                    if a.write and lock not in a.locks:
                        out.append(
                            Violation(
                                f.rel, a.line, self.name, "guarded-by",
                                f"`self.{attr}` written in {a.method} "
                                f"without holding self.{lock} "
                                f"(declared guarded-by)",
                            )
                        )
                continue
            if "owner" in decl:
                owner = aliases.get(decl["owner"], decl["owner"])
                for t, a in pairs:
                    if t != owner:
                        out.append(
                            Violation(
                                f.rel, a.line, self.name, "owner",
                                f"`self.{attr}` accessed from thread "
                                f"`{t}` in {a.method} but declared "
                                f"owner={decl['owner']}",
                            )
                        )
                continue
            # no annotation: flag cross-thread mutation
            if write_threads and len(all_threads) > 1:
                lines = sorted({a.line for _, a in pairs if a.write})
                out.append(
                    Violation(
                        f.rel, lines[0], self.name,
                        "unannotated-shared",
                        f"`{node.name}.{attr}` is written on "
                        f"{sorted(write_threads)} and accessed on "
                        f"{sorted(all_threads)} with no vet "
                        "annotation — declare owner=, guarded-by=, "
                        "or handoff",
                        site=f"{f.rel}::{node.name}.{attr}",
                    )
                )
        return out
