"""devtools: the vet static-analysis suite (driver: scripts/vet.py).

One AST-based driver, pluggable passes, per-pass closed JSON baselines
(reference: src/tidy.zig + src/copyhound.zig — analysis as build step):

- tidy:        source form, unused imports, library prints, named noqa
- copyhound:   host<->device sync inducers on the compute path
- races:       thread-ownership lint over the five thread seams
- determinism: sim-reachable code stays seed-deterministic
"""

from __future__ import annotations

import pathlib

from tigerbeetle_tpu.devtools.base import (
    SourceFile,
    VetPass,
    Violation,
    apply_baseline,
    discover,
    load_baseline,
    load_files,
    save_baseline,
)
from tigerbeetle_tpu.devtools.config import VetConfig, default_config
from tigerbeetle_tpu.devtools.copyhound_pass import CopyhoundPass
from tigerbeetle_tpu.devtools.determinism_pass import DeterminismPass
from tigerbeetle_tpu.devtools.race_pass import RacePass
from tigerbeetle_tpu.devtools.tidy_pass import TidyPass

ALL_PASSES = (TidyPass, CopyhoundPass, RacePass, DeterminismPass)


def make_passes(names: list[str] | None = None) -> list[VetPass]:
    by_name = {p.name: p for p in ALL_PASSES}
    if names is None:
        return [p() for p in ALL_PASSES]
    unknown = [n for n in names if n not in by_name]
    if unknown:
        raise SystemExit(
            f"vet: unknown pass(es): {', '.join(unknown)} "
            f"(have: {', '.join(sorted(by_name))})"
        )
    return [by_name[n]() for n in names]


def baseline_path(config: VetConfig, p: VetPass) -> pathlib.Path | None:
    if p.baseline_name is None:
        return None
    return config.root / "scripts" / p.baseline_name


def run_pass(
    p: VetPass,
    files: list[SourceFile],
    config: VetConfig,
    update: bool = False,
) -> tuple[list[Violation], str | None]:
    """Run one pass through its baseline. Returns (violations, note);
    with update=True the baseline is rewritten first (existing whys
    carried over, new sites left unexplained so the run stays red until
    a human fills them)."""
    violations = p.run(files, config)
    path = baseline_path(config, p)
    if path is None:
        return violations, None
    note = None
    old = load_baseline(path)
    if update:
        sites: dict[str, int] = {}
        for v in violations:
            if v.site:
                sites[v.site] = sites.get(v.site, 0) + 1
        unexplained = save_baseline(path, sites, old)
        note = f"baseline written: {path.name} ({len(sites)} sites"
        note += f", {unexplained} need a why)" if unexplained else ")"
        old = load_baseline(path)
    rel = str(path.relative_to(config.root))
    return apply_baseline(p.name, violations, old, rel), note


def run_vet(
    root: pathlib.Path,
    pass_names: list[str] | None = None,
    update: bool = False,
    config: VetConfig | None = None,
) -> tuple[list[Violation], list[str]]:
    """The whole suite over the repo tree. Returns (violations, notes)."""
    config = config or default_config(root)
    files = load_files(root, discover(root))
    violations: list[Violation] = []
    notes: list[str] = []
    for p in make_passes(pass_names):
        vs, note = run_pass(p, files, config, update=update)
        violations.extend(vs)
        if note:
            notes.append(note)
    return violations, notes
