"""Repo policy for the vet passes: scan sets, seams, allowlists.

Every allowlist entry carries its reason inline — an unexplained
exemption is as bad as an unexplained baseline entry.
"""

from __future__ import annotations

import dataclasses
import pathlib


@dataclasses.dataclass
class VetConfig:
    root: pathlib.Path

    # -- tidy ----------------------------------------------------------
    line_max: int = 100
    # golden-vector fixture tables transcribed verbatim from the
    # reference's test tables keep the reference's own formatting
    line_max_exempt: frozenset = frozenset({"tests/test_golden.py"})
    # user-facing surfaces: print IS their output channel
    print_ok: frozenset = frozenset({
        "tigerbeetle_tpu/cli.py",
        "tigerbeetle_tpu/repl.py",
        "tigerbeetle_tpu/__main__.py",
        "bench.py",
        "__graft_entry__.py",
    })

    # -- copyhound -----------------------------------------------------
    # the device compute path: everywhere a stray sync stalls dispatch
    copyhound_dirs: tuple = (
        "tigerbeetle_tpu/ops/",
        "tigerbeetle_tpu/models/",
        "tigerbeetle_tpu/parallel/",
        "tigerbeetle_tpu/vsr/",
        "tigerbeetle_tpu/lsm/",
        "tigerbeetle_tpu/cdc/",
        "tigerbeetle_tpu/ingress/",
        "tigerbeetle_tpu/io/",
    )
    # attribute holders whose method calls yield device arrays (jitted
    # kernel bundles) for the taint walk
    kernel_holders: tuple = ("self.kernels", "kernels", "self.k")

    # -- races ---------------------------------------------------------
    # the five thread seams (ISSUE 7): WAL writer pool, spill IO
    # executor, device-shadow loop, CDC pump, ingress/bus event loop —
    # plus the metric registry they all write into
    race_scan: frozenset = frozenset({
        "tigerbeetle_tpu/vsr/journal.py",
        "tigerbeetle_tpu/models/spill.py",
        "tigerbeetle_tpu/models/dual_ledger.py",
        "tigerbeetle_tpu/cdc/pump.py",
        "tigerbeetle_tpu/io/message_bus.py",
        "tigerbeetle_tpu/ingress/gateway.py",
        "tigerbeetle_tpu/ingress/fanout.py",
        "tigerbeetle_tpu/metrics.py",
    })
    # annotation names -> inferred thread names. "main" is whatever
    # thread drives the event loop (the server loop, the simulator, a
    # test) — the sequential context every un-spawned method runs on.
    thread_aliases: dict = dataclasses.field(default_factory=lambda: {
        "event-loop": "main",
        "commit": "main",
        "consumer": "main",
    })
    # repo-specific submit-forwarder method names (callables passed in
    # run on that seam's worker), beyond the generic submit/submit_io
    submit_forwarders: tuple = ()

    # -- determinism ---------------------------------------------------
    sim_roots: tuple = (
        "tigerbeetle_tpu/testing/simulator.py",
        "scripts/vopr.py",
        # the prodday harness: the timeline DSL/scorer must stay
        # clock-free (the sim twin replays timelines byte-identically),
        # and the live driver's clock reads must be baselined with whys
        "tigerbeetle_tpu/prodday.py",
        "scripts/prodday.py",
        # the federation composite: per-region Simulators + the sans-IO
        # settlement agent, all tick-driven — no wall clock anywhere
        "tigerbeetle_tpu/federation/sim.py",
    )
    clock_seam: frozenset = frozenset({
        # THE seam: RealTime wraps the OS clocks, DeterministicTime the
        # sim ticks — this is where wall clocks are supposed to live
        "tigerbeetle_tpu/io/time.py",
    })
    # modules inside the static import closure that only prod
    # composition roots construct (reason inline per entry)
    prod_only: dict = dataclasses.field(default_factory=lambda: {
        # observability backends: timing feeds histograms/trace spans,
        # never sim state; the sim asserts on op/state digests only
        "tigerbeetle_tpu/metrics.py":
            "metric timing is observability, not state",
        "tigerbeetle_tpu/tracer.py":
            "trace timestamps are observability, not state "
            "(SimTracer's deterministic dump carries no wall time)",
        "tigerbeetle_tpu/statsd.py":
            "StatsD emission is a prod sink",
        # prod transports/sinks reached via package __init__ imports
        "tigerbeetle_tpu/io/message_bus.py":
            "TCP bus: prod transport, sim uses PacketSimulator",
        "tigerbeetle_tpu/cdc/sink.py":
            "UDP/StatsD/throttle sinks are prod/bench surfaces; the "
            "sim uses in-memory sinks",
        # live-cluster drivers pulled in by scripts/prodday.py: they
        # drive real processes on wall clocks by design; the sim twin
        # reaches the simulator through tigerbeetle_tpu/prodday.py
        # without touching them
        "tigerbeetle_tpu/testing/chaos.py":
            "live chaos harness: subprocess clusters on wall time",
        "tigerbeetle_tpu/benchmark.py":
            "live bench driver: wall-clock load generation",
        "tigerbeetle_tpu/inspect.py":
            "wire inspection client for live servers",
        "tigerbeetle_tpu/artifact.py":
            "artifact provenance (filesystem walks), not sim state",
        "tigerbeetle_tpu/client_ffi.py":
            "FFI client binding (session nonces from OS entropy): prod "
            "client surface, the sim drives vsr/client.py directly",
        "tigerbeetle_tpu/federation/live.py":
            "live two-region driver: subprocess clusters, JSONL tailing "
            "and settlement on wall time; the sim twin is federation/"
            "sim.py on ticks",
    })
    # the executor seam itself + the WAL writer pool: the modules that
    # OWN thread construction behind deterministic alternatives
    executor_seam: dict = dataclasses.field(default_factory=lambda: {
        "tigerbeetle_tpu/models/spill.py":
            "ThreadedSpillIO/DeferredSpillIO IS the seam",
        "tigerbeetle_tpu/vsr/journal.py":
            "the WAL writer pool; deterministic runs use the sync path",
    })


def default_config(root: pathlib.Path) -> VetConfig:
    return VetConfig(root=root)
