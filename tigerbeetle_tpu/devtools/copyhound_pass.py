"""copyhound v2: host<->device sync inducers in the compute path.

The reference's copyhound scans LLVM IR for accidental large memcpys
(reference: src/copyhound.zig). The TPU analog of an accidental memcpy
is an accidental DEVICE SYNC or host round-trip on the commit path: each
one stalls dispatch (see ops/hashtable.py on why dispatch health is the
flagship constraint).

v2 extends the v1 scan (ops/ models/ parallel/) across the whole commit
path — vsr/ lsm/ cdc/ ingress/ io/ — and adds the IMPLICIT inducers v1
missed. Explicit sync calls are matched by name (`np.asarray`,
`.block_until_ready()`, `jax.device_get`, `.tobytes()`, `.item()`,
`from_dlpack`). Implicit inducers are found by a per-function taint
walk: a value produced by `jnp.*` / `jax.*` / a jitted-kernel call (or
read out of a device state dict) is DEVICE-tainted, and

- `float()` / `int()` / `bool()` of a tainted value   -> "coerce"
- any `np.*` call with a tainted argument             -> "np-on-device"
- a tainted value interpolated into an f-string       -> "fstring"

force a transfer the author may not have meant. `np.asarray(x)` yields
a HOST value (that is the sync — counted under "asarray"), so downstream
use of its result is clean.

Every deliberate site lives in the closed baseline
(scripts/copyhound_baseline.json) with a mandatory human `why`.
"""

from __future__ import annotations

import ast

from tigerbeetle_tpu.devtools.base import (
    SourceFile,
    VetPass,
    Violation,
    dotted,
)

SYNC_CALLS = {
    "asarray": "host materialization of a device array",
    "block_until_ready": "explicit device fence",
    "device_get": "explicit device->host transfer",
    "tobytes": "host byte pull",
    "from_dlpack": "host/device buffer handoff",
    "item": "scalar device->host pull",
}

# jax entry points that do NOT produce device values
_JAX_NON_ARRAY = {"jit", "named_scope", "profiler", "config", "devices"}

# functions whose result is host-side even when the argument was tainted
_UNTAINTING = {"asarray", "device_get", "tobytes", "item"}


class _Taint(ast.NodeVisitor):
    """Per-function device-taint walk. One level of local dataflow:
    locals assigned from tainted expressions are tainted; state dicts
    (locals assigned from `<x>.state`) taint their subscripts."""

    def __init__(self, kernel_holders: set[str]):
        self.kernel_holders = kernel_holders
        self.tainted: set[str] = set()
        self.state_dicts: set[str] = set()
        self.hits: list[tuple[int, str, str]] = []  # (line, kind, detail)

    # -- taint predicate ------------------------------------------------

    def is_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            d = dotted(node)
            if d is not None and d.split(".")[0] in self.tainted:
                return True
            return self.is_tainted(node.value)
        if isinstance(node, ast.Subscript):
            if (
                isinstance(node.value, ast.Name)
                and node.value.id in self.state_dicts
            ):
                return True
            return self.is_tainted(node.value)
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            if d is not None:
                root = d.split(".")[0]
                leaf = d.split(".")[-1]
                # jnp.* results are DEVICE arrays — including
                # jnp.asarray, which is h2d staging, not the host
                # materialization np.asarray is; checked before
                # _UNTAINTING so the latter rule can't swallow it
                if root == "jnp":
                    return True
                if root == "jax" and leaf not in _UNTAINTING and (
                    len(d.split(".")) < 2
                    or d.split(".")[1] not in _JAX_NON_ARRAY
                ):
                    return True
                if leaf in _UNTAINTING:
                    return False
                holder = d.rsplit(".", 1)[0]
                if holder in self.kernel_holders:
                    return True
            # a call ON a tainted value (x.astype, x.sum, ...) stays
            # tainted unless the method itself untaints
            if isinstance(node.func, ast.Attribute):
                if node.func.attr in _UNTAINTING:
                    return False
                return self.is_tainted(node.func.value)
            return False
        if isinstance(node, (ast.BinOp,)):
            return self.is_tainted(node.left) or self.is_tainted(node.right)
        if isinstance(node, ast.BoolOp):
            return any(self.is_tainted(v) for v in node.values)
        if isinstance(node, ast.Compare):
            return self.is_tainted(node.left) or any(
                self.is_tainted(c) for c in node.comparators
            )
        if isinstance(node, ast.UnaryOp):
            return self.is_tainted(node.operand)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.is_tainted(e) for e in node.elts)
        if isinstance(node, ast.IfExp):
            return self.is_tainted(node.body) or self.is_tainted(node.orelse)
        return False

    # -- assignment tracking --------------------------------------------

    def _bind(self, target: ast.AST, tainted: bool, state: bool) -> None:
        if isinstance(target, ast.Name):
            if tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
            if state:
                self.state_dicts.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, tainted, state)

    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)
        tainted = self.is_tainted(node.value)
        state = (
            isinstance(node.value, ast.Attribute)
            and node.value.attr == "state"
        )
        for t in node.targets:
            self._bind(t, tainted, state)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self.generic_visit(node)
        if node.value is not None:
            self._bind(
                node.target,
                self.is_tainted(node.value),
                isinstance(node.value, ast.Attribute)
                and node.value.attr == "state",
            )

    # nested defs are walked separately (ast.walk finds every
    # FunctionDef) — do not double-count their bodies here
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- inducer detection ----------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in SYNC_CALLS:
            kind = f.attr
            if f.attr == "asarray" and dotted(f.value) == "jnp":
                # h2d staging: a transfer worth counting, but not the
                # d2h host materialization np.asarray is — a separate
                # site key, so swapping a benign staging upload for a
                # real host sync cannot hide under one baseline count
                kind = "asarray-h2d"
            self.hits.append((node.lineno, kind, kind))
            return
        if isinstance(f, ast.Name) and f.id in SYNC_CALLS:
            self.hits.append((node.lineno, f.id, f.id))
            return
        # keyword-passed device values induce the same transfer as
        # positional ones (`np.sum(a=t)`)
        vals = list(node.args) + [kw.value for kw in node.keywords]
        if isinstance(f, ast.Name) and f.id in ("float", "int", "bool"):
            if any(self.is_tainted(a) for a in vals):
                self.hits.append(
                    (node.lineno, "coerce", f"{f.id}() of a device value")
                )
            return
        d = dotted(f)
        if d is not None and d.split(".")[0] == "np":
            if any(self.is_tainted(a) for a in vals):
                self.hits.append(
                    (node.lineno, "np-on-device",
                     f"{d}() applied to a device value")
                )

    def visit_JoinedStr(self, node: ast.JoinedStr) -> None:
        self.generic_visit(node)
        for part in node.values:
            if isinstance(part, ast.FormattedValue) and self.is_tainted(
                part.value
            ):
                self.hits.append(
                    (node.lineno, "fstring", "device value in an f-string")
                )


class CopyhoundPass(VetPass):
    name = "copyhound"
    doc = __doc__
    baseline_name = "copyhound_baseline.json"
    checks = dict(
        {k: f"explicit sync: {v}" for k, v in SYNC_CALLS.items()},
        **{
            "asarray-h2d": "explicit transfer: h2d staging upload "
                           "(jnp.asarray — result stays on device)",
            "coerce": "float()/int()/bool() coercion of a device value",
            "np-on-device": "numpy ufunc/function applied to a jax array",
            "fstring": "device array interpolated into an f-string",
        },
    )

    def run(self, files: list[SourceFile], config) -> list[Violation]:
        out: list[Violation] = []
        for f in files:
            if not any(f.rel.startswith(d) for d in config.copyhound_dirs):
                continue
            if f.tree is None:
                continue  # tidy reports the syntax error
            holders = set(config.kernel_holders)
            # module scope (and, through it, class bodies — _Taint skips
            # nested FunctionDefs) is a scope like any other: a sync call
            # in a module-level constant or class attribute default must
            # not vanish from the closed baseline just because it is not
            # inside a def (v1's whole-tree walk caught these)
            scopes: list[list] = [list(f.tree.body)]
            for node in ast.walk(f.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    scopes.append(node.body)
            for body in scopes:
                walker = _Taint(holders)
                for stmt in body:
                    walker.visit(stmt)
                for line, kind, detail in walker.hits:
                    out.append(
                        Violation(
                            f.rel, line, self.name, kind,
                            f"host-device sync inducer: {detail} "
                            "(justify in the baseline with a why, "
                            "or remove)",
                            site=f"{f.rel}::{kind}",
                        )
                    )
        return out
