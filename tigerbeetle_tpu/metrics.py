"""Typed event/metric registry: counters, gauges, timing histograms.

The reference pairs a span tracer (src/tracer.zig:48-77) with a StatsD
aggregator (src/statsd.zig:12) and threads them through every stage of the
commit path. This module is the metric half of that pair for our port:

- one `Metrics` registry per process (the composition root creates it and
  hands it to the replica, bus, journal, ledger, spill manager, ...), so
  `bench.py`, `cli.py --statsd` and the `[stats]` shutdown line all read
  the SAME numbers instead of per-site ad-hoc dicts;
- `Counter` / `Gauge` are plain accumulators (float-capable — several
  pipeline stats are cumulative seconds);
- `Histogram` is a fixed-bucket (powers of two, microseconds) timing
  histogram with p50/p95/p99/max snapshots — fixed buckets so recording is
  O(1) with zero allocation on the hot path;
- `StatGroup` is a Mapping view over a prefix of registry counters, kept
  dict-compatible so the pre-existing stat surfaces (`replica.group_stats`,
  `spill.stats`, `shadow_stats`, the server loop accounting) stay readable
  by every existing caller while their storage moves into the registry;
- `NULL_METRICS` is the zero-allocation no-op backend: every handle it
  returns is a shared singleton whose methods do nothing, so permanently
  instrumented hot paths cost one attribute lookup + call when metrics are
  off (the same contract as the `none` tracer backend).

Batched StatsD emission over this registry lives in statsd.StatsDEmitter
(many metrics per MTU-sized datagram, counters as deltas).
"""

from __future__ import annotations

import threading
import time
from collections.abc import Mapping

# Histogram buckets: bucket i holds observations <= 2**i (unit: the
# histogram's unit, microseconds by default). 2^0 us .. 2^26 us (~67 s)
# plus one overflow bucket — timing from a sub-microsecond span to a full
# checkpoint fits without ever resizing.
BUCKETS = 27


class Counter:
    __slots__ = ("name", "unit", "value", "_lock")

    def __init__(self, name: str, unit: str = ""):
        self.name = name
        self.unit = unit
        # One counter is written from several seams at once (the WAL
        # writer pool, the spill IO worker, the device-shadow loop,
        # native-engine done-callbacks). `value += v` is three bytecodes
        # — a thread switch between the read and the store LOSES an
        # increment — so mutation takes the lock (vet: races found the
        # unguarded cross-thread writes this protects against).
        self._lock = threading.Lock()
        self.value = 0  # vet: guarded-by=_lock

    def add(self, v=1) -> None:
        with self._lock:
            self.value += v

    def set(self, v) -> None:  # restore/rebind support
        with self._lock:
            self.value = v


class Gauge:
    __slots__ = ("name", "unit", "value")

    def __init__(self, name: str, unit: str = ""):
        self.name = name
        self.unit = unit
        self.value = 0

    def set(self, v) -> None:
        self.value = v


class _Timed:
    """Context manager: observe the wall time of a block into a histogram
    (microseconds)."""

    __slots__ = ("hist", "t0")

    def __init__(self, hist: "Histogram"):
        self.hist = hist

    def __enter__(self):
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *a):
        self.hist.observe((time.perf_counter_ns() - self.t0) / 1000.0)
        return False


class Histogram:
    """Fixed-bucket timing histogram. observe() is O(1): bit_length of the
    integer value picks the power-of-two bucket. Percentiles come from the
    bucket upper bound, clamped to the true observed max — exact at the
    top, within a factor of two elsewhere (the resolution the reference's
    statsd aggregation works at too)."""

    __slots__ = ("name", "unit", "counts", "count", "total", "max", "_lock")

    def __init__(self, name: str, unit: str = "us"):
        self.name = name
        self.unit = unit
        # Same cross-seam exposure as Counter: journal.write_us is
        # observed from the WAL writer pool while the event loop observes
        # it on the sync path — `count += 1` / `total += v` lose updates
        # on a thread switch, so observe() takes the lock. Reads
        # (percentile/snapshot) stay lock-free: counts never resizes, and
        # a smeared in-flight observation only staleness-skews a report.
        self._lock = threading.Lock()
        self.counts = [0] * (BUCKETS + 1)  # vet: guarded-by=_lock
        self.count = 0   # vet: guarded-by=_lock
        self.total = 0.0  # vet: guarded-by=_lock
        self.max = 0.0   # vet: guarded-by=_lock

    def observe(self, v: float) -> None:
        i = int(v).bit_length()  # v <= 2**i for all v >= 0
        with self._lock:
            self.count += 1
            self.total += v
            if v > self.max:
                self.max = v
            self.counts[i if i <= BUCKETS else BUCKETS] += 1

    def time(self) -> _Timed:
        return _Timed(self)

    def percentile(self, q: float) -> float:
        """Upper bound of the bucket holding the q-quantile observation,
        clamped to the observed max (so p100 == max exactly)."""
        if self.count == 0:
            return 0.0
        rank = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank:
                return min(float(1 << i), self.max)
        return self.max

    def snapshot(self) -> dict:
        return {
            "count": self.count,
            "mean": round(self.total / self.count, 3) if self.count else 0.0,
            "p50": round(self.percentile(0.50), 3),
            "p95": round(self.percentile(0.95), 3),
            "p99": round(self.percentile(0.99), 3),
            "max": round(self.max, 3),
            "unit": self.unit,
        }


class StatGroup(Mapping):
    """Dict-compatible read view over `prefix.key` registry counters.

    Existing stat surfaces keep their shape (`stats["cycles"]`,
    `dict(stats)`, `stats.items()`) while the storage lives in the shared
    registry — the "replace the ad-hoc dicts" move without breaking any
    reader. Writers use .add()."""

    __slots__ = ("_counters",)

    def __init__(self, metrics: "Metrics", prefix: str, keys):
        self._counters = {
            k: metrics.counter(f"{prefix}.{k}") for k in keys
        }

    def add(self, key: str, v=1) -> None:
        self._counters[key].add(v)

    def __getitem__(self, key: str):
        return self._counters[key].value

    def __iter__(self):
        return iter(self._counters)

    def __len__(self):
        return len(self._counters)

    def __repr__(self):
        return repr(dict(self))


class Metrics:
    """The registry: create-once named metrics, full snapshot for the
    [stats] line / bench artifacts / batched StatsD emission."""

    enabled = True

    def __init__(self):
        # REENTRANT: the server's SIGTERM handler snapshots the registry
        # on the same main thread that may be interrupted inside a lazy
        # metric creation — a plain Lock would deadlock the shutdown path
        self._lock = threading.RLock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}

    def counter(self, name: str, unit: str = "") -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(name, unit))
        return c

    def gauge(self, name: str, unit: str = "") -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge(name, unit))
        return g

    def histogram(self, name: str, unit: str = "us") -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(name, Histogram(name, unit))
        return h

    def group(self, prefix: str, keys) -> StatGroup:
        return StatGroup(self, prefix, keys)

    def snapshot(self) -> dict:
        """Point-in-time dump of every registered metric (counters and
        gauges as raw values, histograms as percentile snapshots). The
        registry dicts are copied under the creation lock: worker threads
        (journal writer, spill IO) lazily create metrics on first use,
        and iterating live dicts against a concurrent insert would raise
        mid-flush on the event loop."""
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            histograms = sorted(self._histograms.items())
        return {
            "counters": {
                n: (round(c.value, 6) if isinstance(c.value, float)
                    else c.value)
                for n, c in counters
            },
            "gauges": {n: g.value for n, g in gauges},
            "histograms": {n: h.snapshot() for n, h in histograms},
        }


# -- time-series flight recorder ---------------------------------------


def _rank_percentile(counts, count: int, q: float, vmax: float) -> float:
    """Percentile over a (delta) bucket-count vector: upper bound of the
    bucket holding the q-quantile, clamped to `vmax` (the registry's
    cumulative max — a window has no exact max of its own)."""
    if count <= 0:
        return 0.0
    rank = q * count
    seen = 0
    for i, c in enumerate(counts):
        seen += c
        if seen >= rank:
            return round(min(float(1 << i), vmax), 3)
    return round(vmax, 3)


class FlightRecorder:
    """Fixed-capacity ring of periodic registry snapshots — the metric
    HISTORY a cumulative snapshot cannot give: a 2-second stall inside a
    60-second run is invisible in end-of-run totals, but jumps out of a
    per-interval series ("commit_dispatch_us p99 jumped 40x for 3s
    starting at t=41s").

    Each record() call (the server loop drives it ~1/s) appends one
    compact entry:
      - counters as DELTAS since the previous entry (zero deltas
        dropped — an idle counter costs no history bytes),
      - gauges raw,
      - histograms as WINDOWED percentiles computed from the bucket-
        count deltas (only histograms that observed in the interval),
    so an entry is a few KB and the default 180-entry ring holds ~3
    minutes. The ring rides the `[stats]` wire command as `history`
    (`inspect live --watch` renders it as per-second rates) and the
    SIGQUIT hang dump.

    The caller supplies the timestamp (the server loop's monotonic
    seconds) — the recorder itself reads no clock, so it stays inert in
    the determinism closure."""

    def __init__(self, metrics: Metrics, capacity: int = 180):
        assert capacity > 0
        self.metrics = metrics
        self.capacity = capacity
        self.entries: list[dict] = []  # ring, oldest-first after unwrap
        self._head = 0
        self._prev_t: float | None = None
        self._prev_counters: dict[str, float] = {}
        # histogram window state: name -> (count, total, counts[:])
        self._prev_hist: dict[str, tuple] = {}
        # Scenario phase (the prodday harness's `mark` wire command):
        # every entry recorded while a phase is set carries it, so the
        # SLO scorer slices the ring per phase. Only ever written from
        # the event loop that drives record() (replica._on_mark and the
        # server loop run on the same thread).
        # vet: owner=event-loop
        self.phase: str | None = None
        self.phase_log: list[tuple[float, str]] = []  # (t, name)

    def set_phase(self, name: str, now_s: float | None = None) -> float:
        """Stamp a phase transition: subsequent entries carry `name`.
        With no timestamp the transition is stamped at the last record's
        time base — within one interval of the truth and clock-free, so
        the sim twin's recorder stays inside the determinism closure."""
        t = now_s if now_s is not None else (self._prev_t or 0.0)
        self.phase = name
        self.phase_log.append((round(t, 3), name))
        self.metrics.counter("flight.marks").add()
        return t

    def record(self, now_s: float) -> dict:
        m = self.metrics
        with m._lock:
            counters = list(m._counters.items())
            gauges = list(m._gauges.items())
            histograms = list(m._histograms.items())
        dt = (now_s - self._prev_t) if self._prev_t is not None else None
        self._prev_t = now_s
        c_delta: dict[str, float] = {}
        for name, c in sorted(counters):
            if name == "flight.records":
                continue  # the recorder's own heartbeat: a constant
                # `+1` in every entry is payload noise, not signal
            v = c.value
            d = v - self._prev_counters.get(name, 0)
            if d < 0:
                # the attached registry was swapped for a fresh one (the
                # prodday sim twin re-attaches across a replica restart):
                # count the new registry's value as this interval's delta
                d = v
            if d:
                self._prev_counters[name] = v
                c_delta[name] = round(d, 6) if isinstance(d, float) else d
        h_win: dict[str, dict] = {}
        for name, h in sorted(histograms):
            # lock-free reads (the Histogram contract): a smeared
            # in-flight observation only staleness-skews one interval
            count, total, vmax = h.count, h.total, h.max
            cs = list(h.counts)
            p_count, p_total, p_cs = self._prev_hist.get(
                name, (0, 0.0, None)
            )
            if count < p_count or (
                p_cs is not None
                and any(a < b for a, b in zip(cs, p_cs))
            ):
                # registry swap (see the counter clamp above): total
                # count or any bucket went BACKWARDS, impossible for a
                # monotone histogram — the window restarts from zero
                # against the fresh histogram
                p_count, p_total, p_cs = 0, 0.0, None
            dc = count - p_count
            if dc > 0:
                dcs = (
                    [a - b for a, b in zip(cs, p_cs)]
                    if p_cs is not None else cs
                )
                h_win[name] = {
                    "count": dc,
                    "mean": round((total - p_total) / dc, 3),
                    "p50": _rank_percentile(dcs, dc, 0.50, vmax),
                    "p95": _rank_percentile(dcs, dc, 0.95, vmax),
                    "p99": _rank_percentile(dcs, dc, 0.99, vmax),
                }
                self._prev_hist[name] = (count, total, cs)
        entry = {
            "t": round(now_s, 3),
            "dt": round(dt, 3) if dt is not None else None,
            "counters": c_delta,
            "gauges": {n: g.value for n, g in sorted(gauges)},
            "histograms": h_win,
        }
        if self.phase is not None:
            entry["phase"] = self.phase
        if len(self.entries) < self.capacity:
            self.entries.append(entry)
        else:
            self.entries[self._head] = entry
            self._head = (self._head + 1) % self.capacity
        m.counter("flight.records").add()
        return entry

    def history(self, last: int = 0) -> list[dict]:
        """Entries oldest-first (unwrapping the ring); `last` trims to
        the newest N (the wire snapshot bounds its payload with it)."""
        out = self.entries[self._head:] + self.entries[: self._head]
        return out[-last:] if last else out


# -- the zero-allocation no-op backend ---------------------------------


class _NullTimed:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


_NULL_TIMED = _NullTimed()


class _NullCounter:
    __slots__ = ()
    name = unit = ""
    value = 0

    def add(self, v=1) -> None:
        pass

    def set(self, v) -> None:
        pass


class _NullGauge(_NullCounter):
    __slots__ = ()


class _NullHistogram:
    __slots__ = ()
    name = ""
    unit = "us"
    count = 0
    total = 0.0
    max = 0.0

    def observe(self, v) -> None:
        pass

    def time(self) -> _NullTimed:
        return _NULL_TIMED

    def percentile(self, q) -> float:
        return 0.0

    def snapshot(self) -> dict:
        return {"count": 0}


_NULL_COUNTER = _NullCounter()
_NULL_GAUGE = _NullGauge()
_NULL_HISTOGRAM = _NullHistogram()


class NullMetrics:
    """Every handle is a shared no-op singleton: instrumented hot paths
    stay permanently wired at (attribute lookup + call) cost, with zero
    allocation per event."""

    enabled = False

    def counter(self, name: str, unit: str = "") -> _NullCounter:
        return _NULL_COUNTER

    def gauge(self, name: str, unit: str = "") -> _NullGauge:
        return _NULL_GAUGE

    def histogram(self, name: str, unit: str = "us") -> _NullHistogram:
        return _NULL_HISTOGRAM

    def group(self, prefix: str, keys) -> dict:
        # a PLAIN dict: no-op groups must still be read/writable in place
        # (callers do stats["k"] reads) — a dict of zeros is exactly that,
        # and writers go through .add which dict lacks; null groups are
        # therefore real dicts with an add shim
        return _NullGroup(keys)


class _NullGroup(dict):
    """Readable like the real StatGroup, writes discarded cheaply."""

    def __init__(self, keys):
        super().__init__({k: 0 for k in keys})

    def add(self, key: str, v=1) -> None:
        pass


NULL_METRICS = NullMetrics()


# -- metric-name catalog (units; surfaced in README's observability
# section; the registry does not enforce it — it documents the names the
# instrumented pipeline emits) --

CATALOG = {
    # replica commit pipeline
    "commit.group.fused_ops": ("counter", "ops", "ops committed via a fused group dispatch"),
    "commit.group.solo_ops": ("counter", "ops", "ops committed via the per-op fallback"),
    "commit.group.fused_groups": ("counter", "groups", "fused group dispatches"),
    "commit.group.fuse_holds": ("counter", "", "fuse-window holds opened on a short run"),
    "commit.group.fuse_expired": ("counter", "", "holds expired with the run still short"),
    "commit.group.wave_ops": ("counter", "ops", "ops committed via the conflict-wave scheduler"),
    "commit.group.wave_dispatches": (
        "counter", "waves", "waves dispatched across wave-scheduled ops"
    ),
    # conflict-wave scheduler (models/ledger.py HazardTracker.plan +
    # DeviceLedger._execute_waves)
    "waves.batches": ("counter", "", "batches executed through the wave scheduler"),
    "waves.per_batch": ("histogram", "waves", "dependency-ordered waves per scheduled batch"),
    "waves.chain_len_max": ("gauge", "waves", "deepest dependency chain wave-executed so far"),
    "waves.occupancy": ("gauge", "", "active-lane fraction per wave of the last scheduled batch"),
    "waves.residue_events": ("counter", "events", "events that fell to the serial residue"),
    "replica.quorum_wait_us": ("histogram", "us", "prepare broadcast -> replication quorum"),
    "replica.fuse_hold_us": ("histogram", "us", "group-commit fuse-window hold duration"),
    "replica.commit_dispatch_us": ("histogram", "us", "host time staging+launching one commit"),
    "replica.commit_finalize_us": ("histogram", "us", "drain + reply build + reply-slot write"),
    "replica.checkpoint_us": ("histogram", "us", "durable checkpoint (snapshot + trailers)"),
    "replica.checkpoints": ("counter", "", "checkpoints taken"),
    "grid.repair_requests": ("counter", "", "block repair rounds requested from peers"),
    # journal
    "journal.write_us": ("histogram", "us", "WAL prepare+header write (sync or worker)"),
    "journal.writes": ("counter", "", "prepares written to the WAL"),
    # message bus
    "bus.frames": ("counter", "", "frames parsed and dispatched"),
    "bus.tx_bytes": ("counter", "bytes", "bytes written to sockets"),
    "bus.flushes": ("counter", "", "deferred-send flush passes"),
    "bus.pump_us": ("histogram", "us", "event-loop pump turns that dispatched frames"),
    "bus.reconnects": ("counter", "conns", "successful re-dials to a previously reached replica"),
    "bus.dial_failures": ("counter", "", "dials refused/errored (arms the reconnect backoff)"),
    # client runtime (vsr/client.py tick state machine)
    "client.timeouts": ("counter", "", "request timeouts fired (loss ladder)"),
    "client.resends": ("counter", "", "request retransmissions (timeout, busy, legacy resend)"),
    "client.retargets": ("counter", "", "timeout resends aimed off-primary (round-robin walk)"),
    "client.busy_sheds": ("counter", "", "typed busy replies accepted for the in-flight request"),
    "client.pings": ("counter", "", "idle ping_client rounds (view discovery)"),
    "client.pongs": ("counter", "", "pong_client replies (view learned while idle)"),
    "client.evictions": ("counter", "", "sessions evicted by the cluster"),
    "client.reregisters": ("counter", "", "automatic post-eviction re-registrations"),
    "client.deadline_timeouts": ("counter", "", "requests dropped at their per-request deadline"),
    "client.stale_replies": ("counter", "", "duplicate/stale replies ignored (dedup)"),
    # live chaos harness (testing/chaos.py)
    "chaos.kills": ("counter", "", "replica processes SIGKILLed"),
    "chaos.restarts": ("counter", "", "replica processes respawned"),
    "chaos.gray_stops": ("counter", "", "SIGSTOP gray failures injected"),
    "chaos.conn_resets": ("counter", "", "client connection reset storms injected"),
    "chaos.recovery_ms": ("histogram", "ms", "fault to first client reply after it"),
    # server event loop (cli.py)
    "loop.busy_s": ("counter", "s", "event-loop busy wall time (pump+commit+flush)"),
    "loop.turns": ("counter", "", "busy event-loop turns"),
    "server.ops_committed": ("counter", "ops", "ops committed since boot"),
    "server.commit_min": ("gauge", "op", "highest committed op"),
    # LSM
    "lsm.lookup_batches": ("counter", "", "batched multi-point-reads (Tree.get_many)"),
    "lsm.lookup_ids": ("counter", "", "ids resolved through get_many"),
    "lsm.bloom_probes": ("counter", "", "per-table bloom-filter probes"),
    "lsm.bloom_negatives": ("counter", "", "candidates pruned by a bloom filter"),
    "lsm.get_many_us": ("histogram", "us", "one batched multi-point-read"),
    "lsm.compact_us": ("histogram", "us", "one tree settle/compaction step"),
    "grid.block_reads": ("counter", "", "block-cache misses read from storage"),
    "grid.corrupt_blocks": ("counter", "", "reads that tripped GridBlockCorrupt"),
    # spill pipeline (models/spill.py `spill.*` StatGroup + timings)
    "spill.cycles": ("counter", "", "spill cycles (cold tail -> LSM)"),
    "spill.spilled": ("counter", "rows", "rows spilled to the forest"),
    "spill.reloaded": ("counter", "rows", "spilled rows reloaded into HBM"),
    "spill.prefetches": ("counter", "", "prefetch_async jobs started"),
    "spill.prefetched": ("counter", "rows", "rows served from a prefetch"),
    "spill.t_prefetch_worker": ("counter", "s", "executor seconds gathering prefetched rows"),
    "spill.t_prefetch_wait": ("counter", "s", "seconds admit blocked on an unfinished prefetch"),
    "spill.staging_wait_us": ("histogram", "us", "reload staging-slot fence waits"),
    "spill.admit_us": ("histogram", "us", "pre-commit admission (reload + cycle)"),
    # device shadow (models/dual_ledger.py `shadow.*` StatGroup)
    "shadow.batches": ("counter", "", "batches applied by the device shadow"),
    "shadow.groups": ("counter", "", "fused shadow group dispatches"),
    "shadow.solo": ("counter", "", "per-batch shadow dispatches"),
    "shadow.stage_s": ("counter", "s", "host seconds staging+dispatching shadow work"),
    "shadow.idle_s": ("counter", "s", "shadow loop seconds blocked on an empty queue"),
    "shadow.overlapped": ("counter", "", "groups staged while the previous kernel ran"),
    # dual-commit follower mode (`--backend dual`)
    "shadow.device_lag_ops": ("gauge", "ops", "committed ops not yet device-dispatched"),
    "shadow.device_apply_overlap": ("gauge", "", "fused applies staged while the prior kernel ran"),
    "shadow.drain_timeouts": ("counter", "", "applier drains that timed out (parity at risk)"),
    # device ledger
    "ledger.staging_wait_us": ("histogram", "us", "group staging double-buffer fence waits"),
    # change-data-capture (tigerbeetle_tpu/cdc/pump.py)
    "cdc.ops": ("counter", "ops", "committed ops streamed (gap spans excluded)"),
    "cdc.records": ("counter", "records", "change records accepted by the sink"),
    "cdc.gap_ops": ("counter", "ops", "ops covered by declared gap records"),
    "cdc.lag_ops": ("gauge", "ops", "commit_min minus the next un-streamed op"),
    "cdc.backpressure_pauses": ("counter", "", "pump pauses on a refusing sink (transitions)"),
    "cdc.live_hits": ("counter", "ops", "ops served from the live hook window"),
    "cdc.journal_reads": ("counter", "ops", "ops re-read from the WAL ring"),
    "cdc.aof_reads": ("counter", "ops", "ops replayed from the AOF (oracle-derived results)"),
    "cdc.results_unknown": ("counter", "ops", "create ops streamed without a reply buffer"),
    "cdc.resume_forks": ("counter", "", "cursor checksum mismatches detected at resume"),
    "cdc.cursor_writes": ("counter", "", "durable cursor acks (atomic write-rename)"),
    "cdc.pump_us": ("histogram", "us", "one bounded pump turn (encode + emit)"),
    "cdc.commitment_records": (
        "counter", "records", "checkpoint state-commitment records emitted"
    ),
    # cross-ledger federation (tigerbeetle_tpu/federation): the
    # settlement agent's per-region counters — at-least-once delivery
    # means the leg counters can exceed unique-event counts across agent
    # crash/redelivery (the conservation check is the authority)
    "federation.inflight_legs": (
        "gauge", "legs", "settlement legs staged and unresolved in the agent window"
    ),
    "federation.outbound_seen": (
        "counter", "legs", "outbound origin pendings recognized in the stream"
    ),
    "federation.legs_posted": (
        "counter", "legs", "origin pendings settled (mirror leg ok, origin posted)"
    ),
    "federation.legs_voided": (
        "counter", "legs", "origin pendings voided (mirror leg terminally rejected)"
    ),
    "federation.sink_refusals": (
        "counter", "", "ops refused at the agent window (pump retries them)"
    ),
    "federation.anomalies": (
        "counter", "legs", "resolve replies outside the expected code family"
    ),
    # ingress gateway + bus front door (tigerbeetle_tpu/ingress)
    "ingress.sessions": ("gauge", "sessions", "live logical sessions in the gateway table"),
    "ingress.admitted": ("counter", "requests", "requests admitted by the credit regulator"),
    "ingress.shed": ("counter", "requests", "requests answered with a typed busy reply"),
    "ingress.shed_sessions": ("counter", "requests", "new sessions shed at the gateway cap"),
    "ingress.retransmits": ("counter", "requests", "retransmits bypassing admission"),
    "ingress.passthrough_backup": (
        "counter", "requests", "requests passed through on a non-primary"
    ),
    "ingress.accepts": ("counter", "conns", "connections taken by the accept-drain loop"),
    "ingress.shed_conn": ("counter", "sends", "sends refused at a per-connection queue cap"),
    "ingress.shed_pool": ("counter", "sends", "sends refused at the shared message-pool budget"),
    "ingress.disconnect_wedged": ("counter", "conns", "wedged consumers cut at the strike limit"),
    "ingress.fanout_consumers": ("gauge", "consumers", "CDC fan-out consumers on one tail"),
    "ingress.fanout_lag_ops": ("gauge", "ops", "slowest fan-out consumer vs the watermark"),
    # per-request critical-path attribution (tigerbeetle_tpu/latency.py;
    # legs are CONSECUTIVE intervals, so a request's legs sum to its e2e)
    "latency.ingress_admission_us": (
        "histogram", "us", "arrival/gateway admit -> request admission+dedup done"
    ),
    "latency.wal_write_us": (
        "histogram", "us", "prepare built + WAL write issued (sync path: completed)"
    ),
    "latency.quorum_wait_us": (
        "histogram", "us", "prepare broadcast -> replication quorum reached"
    ),
    "latency.fuse_hold_us": (
        "histogram", "us", "quorum-ready -> commit dispatch entry (group-fuse hold)"
    ),
    "latency.commit_dispatch_us": (
        "histogram", "us", "commit dispatch (stage + device launch)"
    ),
    "latency.commit_wait_us": (
        "histogram", "us", "dispatch -> finalize entry (async window / device compute)"
    ),
    "latency.commit_finalize_us": (
        "histogram", "us", "finalize (WAL ack wait + drain + reply build)"
    ),
    "latency.reply_egress_us": (
        "histogram", "us", "reply built -> reply leaves (bus flush / send)"
    ),
    "latency.e2e_us": (
        "histogram", "us", "arrival -> reply egress (the legs above sum to this)"
    ),
    "latency.samples": ("counter", "requests", "requests stamped end to end"),
    "latency.dropped": (
        "counter", "requests", "open records evicted unfinished (shed/lost replies)"
    ),
    # parallel lanes (observed off the critical path, never in e2e)
    "latency.device_apply_lag_us": (
        "histogram", "us", "dual mode: commit finalize enqueue -> device upload"
    ),
    "latency.wal_lane_us": (
        "histogram", "us", "async WAL: submit -> durable on the writer pool"
    ),
    # device applier anatomy (latency.py DeviceAnatomy, stamped by
    # models/dual_ledger.py's apply loop; sub-legs are CONSECUTIVE, so a
    # sampled item's sub-legs sum to its apply_e2e exactly — this is the
    # decomposition of the replica's commit_wait leg)
    "device.queue_wait_us": (
        "histogram", "us", "apply_commit enqueue -> apply-loop dequeue"
    ),
    "device.coalesce_hold_us": (
        "histogram", "us", "dequeue -> item's stretch enters staging (run assembly)"
    ),
    "device.h2d_stage_us": (
        "histogram", "us", "staging entry -> h2d upload issued (group path)"
    ),
    "device.dispatch_us": (
        "histogram", "us", "upload issued -> kernel dispatch call returned"
    ),
    "device.device_busy_us": (
        "histogram", "us", "dispatch -> fold digest fence ready (device compute)"
    ),
    "device.finalize_visible_us": (
        "histogram", "us", "fence ready -> applied counters/parity visible"
    ),
    "device.apply_e2e_us": (
        "histogram", "us", "enqueue -> finalize-visible (the sub-legs sum to this)"
    ),
    "device.samples": ("counter", "items", "apply items stamped end to end"),
    # device applier throughput surfaces (flight-recorder device columns)
    "device.queue_depth": ("gauge", "items", "apply-queue depth at the last dequeue"),
    "device.h2d_bytes": ("counter", "bytes", "event bytes staged for device upload"),
    "device.dispatches": ("counter", "", "device kernel dispatches (group or solo)"),
    # compile sentinel (models/ledger.py CompileSentinel wrapping every
    # jit entry point; post-warmup compiles are hot-path events)
    "device.compiles": ("counter", "", "XLA compiles observed at any jit entry point"),
    "device.compiles_post_warmup": (
        "counter", "", "compiles landing AFTER warmup — hot-path recompile events"
    ),
    "device.compile_ms": ("histogram", "ms", "wall time of one observed XLA compile"),
    # XLA trace bridge (--device-trace profiler window on the applier)
    "device.trace_windows": ("counter", "", "bounded jax.profiler windows captured"),
    # time-series flight recorder (metrics.py FlightRecorder)
    "flight.records": ("counter", "", "flight-recorder snapshots taken"),
    "flight.marks": ("counter", "", "phase-marker transitions stamped (prodday `mark`)"),
    "inspect.marks": ("counter", "", "`mark` wire commands served (vsr/replica.py _on_mark)"),
    # cluster-causal tracing + introspection (tracer.py, inspect.py)
    "trace.sigquit_dumps": ("counter", "", "SIGQUIT hang-diagnosis dumps taken"),
    "inspect.live_requests": ("counter", "", "live [stats] snapshots served over the wire"),
    # bench driver
    "bench.batch_latency_us": ("histogram", "us", "synced single-batch dispatch latency"),
}
