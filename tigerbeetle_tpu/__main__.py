from tigerbeetle_tpu.cli import main

raise SystemExit(main())
