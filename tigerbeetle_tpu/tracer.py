"""Span tracer (reference: src/tracer.zig:48-77 — commit/prefetch/compact/
io spans, backends none|Tracy).

Backends here: `none` (no-op, zero overhead) and `json` (in-memory ring of
spans dumped in Chrome trace-event format — load in about://tracing or
Perfetto). Spans nest; the commit path and the bench driver emit them.
"""

from __future__ import annotations

import json
import time


class Tracer:
    """No-op base (the `none` backend)."""

    def start(self, name: str, **args) -> int:
        return 0

    def stop(self, token: int) -> None:
        pass

    def span(self, name: str, **args):
        return _NullSpan()

    def dump(self, path: str) -> None:
        pass


class _NullSpan:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class JsonTracer(Tracer):
    def __init__(self, capacity: int = 65536):
        self.events: list[dict] = []
        self.capacity = capacity
        self._next = 1
        self._open: dict[int, tuple[str, int, dict]] = {}

    def start(self, name: str, **args) -> int:
        token = self._next
        self._next += 1
        self._open[token] = (name, time.perf_counter_ns(), args)
        return token

    def stop(self, token: int) -> None:
        name, t0, args = self._open.pop(token)
        if len(self.events) < self.capacity:
            self.events.append({
                "name": name,
                "ph": "X",  # complete event
                "ts": t0 / 1000,  # Chrome traces are in microseconds
                "dur": (time.perf_counter_ns() - t0) / 1000,
                "pid": 0,
                "tid": 0,
                "args": args,
            })

    def span(self, name: str, **args):
        return _Span(self, name, args)

    def dump(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"traceEvents": self.events}, f)


class _Span:
    __slots__ = ("tracer", "name", "args", "token")

    def __init__(self, tracer: JsonTracer, name: str, args: dict):
        self.tracer = tracer
        self.name = name
        self.args = args

    def __enter__(self):
        self.token = self.tracer.start(self.name, **self.args)
        return self

    def __exit__(self, *a):
        self.tracer.stop(self.token)
        return False
