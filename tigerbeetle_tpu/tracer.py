"""Span tracer (reference: src/tracer.zig:48-77 — commit/prefetch/compact/
io spans, backends none|Tracy).

Backends here:

- `none` (the default everywhere): zero overhead — start/stop do nothing
  and span() returns a shared singleton context manager, so hot paths stay
  permanently instrumented (the CI smoke test pins the per-span cost);
- `json` (JsonTracer): an in-memory RING of spans dumped in Chrome
  trace-event format — load in about://tracing or Perfetto. When the ring
  is full the OLDEST events are overwritten (a long run keeps its tail,
  the part you are debugging); spans still open at dump() are emitted as
  incomplete `ph: "B"` events rather than silently dropped.
- deterministic (SimTracer / any JsonTracer with a virtual clock): spans
  are timestamped with SIMULATOR TICKS instead of wall time, and dump()
  writes canonical JSON (sorted keys, fixed separators) — the same VOPR
  seed produces a byte-identical trace across runs, so two dumps can be
  diffed when a seed diverges.

Spans nest; the commit path, message bus, journal, LSM, spill pipeline and
the bench driver emit them. A JsonTracer constructed with `metrics=` also
feeds each completed span's duration into the registry histogram
`span.<name>` (tigerbeetle_tpu/metrics.py), so trace runs get percentile
snapshots for free.
"""

from __future__ import annotations

import json
import threading
import time


class Tracer:
    """No-op base (the `none` backend)."""

    enabled = False

    def start(self, name: str, **args) -> int:
        return 0

    def stop(self, token: int) -> None:
        pass

    def annotate(self, token: int, **args) -> None:
        """Attach args to a still-open span (facts learned mid-span, e.g.
        the trace ids of the frames a bus.frame_parse pass dispatched)."""

    def span(self, name: str, **args):
        return _NULL_SPAN

    def dump(self, path: str) -> None:
        pass


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


_NULL_SPAN = _NullSpan()
NULL_TRACER = Tracer()


class JsonTracer(Tracer):
    """Ring of complete events in Chrome trace format.

    `clock` defaults to wall time (perf_counter_ns; ts_div=1000 converts
    to the microseconds Chrome traces use). A deterministic harness passes
    a virtual clock (ticks) and ts_div=1.0 — see SimTracer."""

    enabled = True

    def __init__(self, capacity: int = 65536, clock=None,
                 ts_div: float = 1000.0, metrics=None, pid: int = 0):
        assert capacity > 0
        self.events: list[dict] = []
        self.capacity = capacity
        self.clock = clock if clock is not None else time.perf_counter_ns
        self.ts_div = ts_div
        self.metrics = metrics  # optional: span durations -> histograms
        self.pid = pid
        self._next = 1
        self._head = 0  # ring overwrite position once at capacity
        self._open: dict[int, tuple[str, int, dict]] = {}
        # spans stop from worker threads too (journal writer, spill IO).
        # REENTRANT: the server's SIGTERM handler dumps the trace on the
        # same main thread that may be interrupted inside start()/stop() —
        # a plain Lock would deadlock the shutdown dump.
        self._lock = threading.RLock()

    def start(self, name: str, **args) -> int:
        with self._lock:
            token = self._next
            self._next += 1
            self._open[token] = (name, self.clock(), args)
        return token

    def annotate(self, token: int, **args) -> None:
        with self._lock:
            entry = self._open.get(token)
            if entry is not None:
                entry[2].update(args)

    def stop(self, token: int) -> None:
        now = self.clock()
        with self._lock:
            name, t0, args = self._open.pop(token)
            event = {
                "name": name,
                "ph": "X",  # complete event
                "ts": t0 / self.ts_div,
                "dur": (now - t0) / self.ts_div,
                "pid": self.pid,
                "tid": 0,
                "args": args,
            }
            if len(self.events) < self.capacity:
                self.events.append(event)
            else:
                # ring: overwrite the oldest (keep the newest tail)
                self.events[self._head] = event
                self._head = (self._head + 1) % self.capacity
        if self.metrics is not None:
            if self.ts_div == 1000.0:  # wall clock: dur is already ns
                self.metrics.histogram(f"span.{name}").observe(
                    (now - t0) / 1000.0
                )

    def span(self, name: str, **args):
        return _Span(self, name, args)

    def events_ordered(self) -> list[dict]:
        """Events oldest-first (unwrapping the ring), then any still-open
        spans as incomplete `ph: "B"` begin events."""
        with self._lock:
            out = self.events[self._head:] + self.events[: self._head]
            for token in sorted(self._open):
                name, t0, args = self._open[token]
                out.append({
                    "name": name,
                    "ph": "B",  # begin without end: incomplete at dump
                    "ts": t0 / self.ts_div,
                    "pid": self.pid,
                    "tid": 0,
                    "args": args,
                })
            return out

    def dump(self, path: str) -> None:
        # canonical encoding (sorted keys, fixed separators): with a
        # deterministic clock the dump is byte-identical across runs
        with open(path, "w") as f:
            json.dump({"traceEvents": self.events_ordered()}, f,
                      sort_keys=True, separators=(",", ":"))


class SimTracer(JsonTracer):
    """Deterministic tracer for the simulator/VOPR: timestamps are sim
    ticks (the virtual clock the whole cluster runs on), so a seed's trace
    is byte-identical across runs and two dumps of a diverging seed can be
    diffed line by line."""

    def __init__(self, clock, capacity: int = 65536, pid: int = 0):
        super().__init__(capacity=capacity, clock=clock, ts_div=1.0,
                         pid=pid)


# -- cluster-causal stitching ------------------------------------------
#
# Spans tagged with a trace id (args `trace` = one u64, or `traces` = a
# list of them — vsr/header.py trace_id) become Perfetto FLOW events at
# stitch time: for each id that appears in at least two spans, the first
# occurrence emits a flow-start ("s"), the last a flow-end ("f", bound to
# the enclosing slice), and everything between a step ("t") — clicking
# any leg of an op in Perfetto then draws arrows through its whole
# causal tree across processes. Flows are GENERATED from the surviving
# span events (never recorded into the ring), so a ring that overwrote
# an op's early spans simply shortens its flow — a dangling flow id is
# impossible by construction, and stitching is a pure deterministic
# function of the dumps (same-seed simulator runs stitch byte-identical).


def _span_trace_ids(event: dict) -> list[int]:
    args = event.get("args") or {}
    out = []
    t = args.get("trace")
    if t:
        out.append(t)
    for t in args.get("traces") or ():
        if t:
            out.append(t)
    return out


def flow_events(events: list[dict]) -> list[dict]:
    """Generate s/t/f flow events from the trace tags of `events`
    (complete or incomplete span events, any mix of pids). Ids seen in
    only ONE span emit nothing — a one-point flow is noise and a lone
    start would dangle."""
    occurrences: dict[int, list[tuple]] = {}
    for i, e in enumerate(events):
        if e.get("ph") not in ("X", "B"):
            continue
        for t in _span_trace_ids(e):
            occurrences.setdefault(t, []).append(
                (e["ts"], e["pid"], e.get("tid", 0), i)
            )
    flows: list[dict] = []
    for t in sorted(occurrences):
        occ = occurrences[t]
        if len(occ) < 2:
            continue
        occ.sort()  # (ts, pid, tid, event index): canonical causal order
        for j, (ts, pid, tid, _i) in enumerate(occ):
            ph = "s" if j == 0 else ("f" if j == len(occ) - 1 else "t")
            ev = {
                "ph": ph,
                "cat": "op",
                "name": "op",
                "id": f"{t:x}",
                "ts": ts,
                "pid": pid,
                "tid": tid,
            }
            if ph == "f":
                ev["bp"] = "e"  # bind the end to the ENCLOSING slice
            flows.append(ev)
    return flows


def stitch(event_lists: list[list[dict]],
           labels: list[str] | None = None) -> list[dict]:
    """Merge per-process span dumps into ONE event list: dump i's events
    are re-assigned pid=i (each process traced with its own local pid 0),
    named via process_name metadata, and the cross-process flow events
    are generated over the union. Pure + deterministic: byte-identical
    inputs stitch byte-identically."""
    out: list[dict] = []
    for pid in range(len(event_lists)):
        label = labels[pid] if labels and pid < len(labels) else f"pid {pid}"
        out.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "ts": 0, "args": {"name": label},
        })
    for pid, events in enumerate(event_lists):
        for e in events:
            out.append(dict(e, pid=pid))
    out.extend(flow_events(out))
    return out


def dump_stitched(path: str, event_lists: list[list[dict]],
                  labels: list[str] | None = None) -> int:
    """Write a stitched trace as canonical JSON (sorted keys, fixed
    separators — the same byte-reproducibility contract as
    JsonTracer.dump). Returns the stitched event count."""
    events = stitch(event_lists, labels)
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f,
                  sort_keys=True, separators=(",", ":"))
    return len(events)


class _Span:
    __slots__ = ("tracer", "name", "args", "token")

    def __init__(self, tracer: JsonTracer, name: str, args: dict):
        self.tracer = tracer
        self.name = name
        self.args = args

    def __enter__(self):
        self.token = self.tracer.start(self.name, **self.args)
        return self

    def __exit__(self, *a):
        self.tracer.stop(self.token)
        return False
