"""Span tracer (reference: src/tracer.zig:48-77 — commit/prefetch/compact/
io spans, backends none|Tracy).

Backends here:

- `none` (the default everywhere): zero overhead — start/stop do nothing
  and span() returns a shared singleton context manager, so hot paths stay
  permanently instrumented (the CI smoke test pins the per-span cost);
- `json` (JsonTracer): an in-memory RING of spans dumped in Chrome
  trace-event format — load in about://tracing or Perfetto. When the ring
  is full the OLDEST events are overwritten (a long run keeps its tail,
  the part you are debugging); spans still open at dump() are emitted as
  incomplete `ph: "B"` events rather than silently dropped.
- deterministic (SimTracer / any JsonTracer with a virtual clock): spans
  are timestamped with SIMULATOR TICKS instead of wall time, and dump()
  writes canonical JSON (sorted keys, fixed separators) — the same VOPR
  seed produces a byte-identical trace across runs, so two dumps can be
  diffed when a seed diverges.

Spans nest; the commit path, message bus, journal, LSM, spill pipeline and
the bench driver emit them. A JsonTracer constructed with `metrics=` also
feeds each completed span's duration into the registry histogram
`span.<name>` (tigerbeetle_tpu/metrics.py), so trace runs get percentile
snapshots for free.
"""

from __future__ import annotations

import json
import threading
import time


class Tracer:
    """No-op base (the `none` backend)."""

    enabled = False

    def start(self, name: str, **args) -> int:
        return 0

    def stop(self, token: int) -> None:
        pass

    def span(self, name: str, **args):
        return _NULL_SPAN

    def dump(self, path: str) -> None:
        pass


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


_NULL_SPAN = _NullSpan()
NULL_TRACER = Tracer()


class JsonTracer(Tracer):
    """Ring of complete events in Chrome trace format.

    `clock` defaults to wall time (perf_counter_ns; ts_div=1000 converts
    to the microseconds Chrome traces use). A deterministic harness passes
    a virtual clock (ticks) and ts_div=1.0 — see SimTracer."""

    enabled = True

    def __init__(self, capacity: int = 65536, clock=None,
                 ts_div: float = 1000.0, metrics=None, pid: int = 0):
        assert capacity > 0
        self.events: list[dict] = []
        self.capacity = capacity
        self.clock = clock if clock is not None else time.perf_counter_ns
        self.ts_div = ts_div
        self.metrics = metrics  # optional: span durations -> histograms
        self.pid = pid
        self._next = 1
        self._head = 0  # ring overwrite position once at capacity
        self._open: dict[int, tuple[str, int, dict]] = {}
        # spans stop from worker threads too (journal writer, spill IO).
        # REENTRANT: the server's SIGTERM handler dumps the trace on the
        # same main thread that may be interrupted inside start()/stop() —
        # a plain Lock would deadlock the shutdown dump.
        self._lock = threading.RLock()

    def start(self, name: str, **args) -> int:
        with self._lock:
            token = self._next
            self._next += 1
            self._open[token] = (name, self.clock(), args)
        return token

    def stop(self, token: int) -> None:
        now = self.clock()
        with self._lock:
            name, t0, args = self._open.pop(token)
            event = {
                "name": name,
                "ph": "X",  # complete event
                "ts": t0 / self.ts_div,
                "dur": (now - t0) / self.ts_div,
                "pid": self.pid,
                "tid": 0,
                "args": args,
            }
            if len(self.events) < self.capacity:
                self.events.append(event)
            else:
                # ring: overwrite the oldest (keep the newest tail)
                self.events[self._head] = event
                self._head = (self._head + 1) % self.capacity
        if self.metrics is not None:
            if self.ts_div == 1000.0:  # wall clock: dur is already ns
                self.metrics.histogram(f"span.{name}").observe(
                    (now - t0) / 1000.0
                )

    def span(self, name: str, **args):
        return _Span(self, name, args)

    def events_ordered(self) -> list[dict]:
        """Events oldest-first (unwrapping the ring), then any still-open
        spans as incomplete `ph: "B"` begin events."""
        with self._lock:
            out = self.events[self._head:] + self.events[: self._head]
            for token in sorted(self._open):
                name, t0, args = self._open[token]
                out.append({
                    "name": name,
                    "ph": "B",  # begin without end: incomplete at dump
                    "ts": t0 / self.ts_div,
                    "pid": self.pid,
                    "tid": 0,
                    "args": args,
                })
            return out

    def dump(self, path: str) -> None:
        # canonical encoding (sorted keys, fixed separators): with a
        # deterministic clock the dump is byte-identical across runs
        with open(path, "w") as f:
            json.dump({"traceEvents": self.events_ordered()}, f,
                      sort_keys=True, separators=(",", ":"))


class SimTracer(JsonTracer):
    """Deterministic tracer for the simulator/VOPR: timestamps are sim
    ticks (the virtual clock the whole cluster runs on), so a seed's trace
    is byte-identical across runs and two dumps of a diverging seed can be
    diffed line by line."""

    def __init__(self, clock, capacity: int = 65536, pid: int = 0):
        super().__init__(capacity=capacity, clock=clock, ts_div=1.0,
                         pid=pid)


class _Span:
    __slots__ = ("tracer", "name", "args", "token")

    def __init__(self, tracer: JsonTracer, name: str, args: dict):
        self.tracer = tracer
        self.name = name
        self.args = args

    def __enter__(self):
        self.token = self.tracer.start(self.name, **self.args)
        return self

    def __exit__(self, *a):
        self.tracer.stop(self.token)
        return False
