"""CdcFanoutHub: one change-stream tail, N independent consumers.

PR 4's CdcPump bound one consumer (sink + cursor) to one live window;
serving N sinks meant N windows and N hook chains, or one fan-out sink
whose slowest member backpressured everyone. The hub fixes both:

- ONE `CdcTail` (cdc/pump.py) holds the shared live window and the
  WAL-ring fallback — reads are non-destructive, so every consumer
  reads the same ops at its own position (the deep AOF-replay source is
  per-consumer: it is forward-only, tracking ONE position);
- each consumer is a full `CdcPump` (its own cursor, sink, pause state,
  ack cadence) constructed over the shared tail — pausing, crashing or
  resuming one consumer never moves another's position;
- the hub releases the live window at the SLOWEST consumer's position,
  and the window stays bounded regardless: a consumer lagging past
  `window` ops falls back to WAL-ring (then AOF) reads while the fast
  consumers keep riding the O(1) live window. Backpressure isolation
  is therefore structural, not scheduled.

Budgeting: `pump(budget_ops)` gives EVERY consumer its own budget per
turn (a paused consumer spends none of it — its sink refusal returns
immediately), so one throttled sink cannot starve the others' turns.
"""

from __future__ import annotations

from tigerbeetle_tpu.cdc.pump import CdcPump, CdcTail


class CdcFanoutHub:
    def __init__(self, replica, window: int = 256,
                 aof_path: str | None = None):
        self.replica = replica
        self.tail = CdcTail(replica, window=window, aof_path=aof_path)
        self.pumps: dict[str, CdcPump] = {}
        self._attached = False
        m = replica.metrics
        self._g_consumers = m.gauge("ingress.fanout_consumers")
        self._g_lag = m.gauge("ingress.fanout_lag_ops")

    def add_consumer(self, name: str, sink, cursor,
                     ack_interval: int = 32,
                     commitments: bool = False) -> CdcPump:
        assert name not in self.pumps, f"duplicate consumer {name!r}"
        pump = CdcPump(
            self.replica, sink, cursor,
            window=self.tail.window, ack_interval=ack_interval,
            tail=self.tail, commitments=commitments,
        )
        self.pumps[name] = pump
        self._g_consumers.set(len(self.pumps))
        return pump

    def remove_consumer(self, name: str) -> None:
        pump = self.pumps.pop(name)
        pump.flush()
        self._g_consumers.set(len(self.pumps))
        self._release()

    # -- lifecycle (the hub owns the shared tail's hook) --

    def attach(self) -> None:
        if not self._attached:
            self.tail.attach()
            self._attached = True

    def detach(self) -> None:
        if self._attached:
            self.tail.detach()
            self._attached = False

    # -- stream progress --

    def pump(self, budget_ops: int = 8) -> int:
        """One bounded turn per consumer; returns total ops streamed.
        Never blocks, never touches the commit path (the per-consumer
        CdcPump contract, N times over)."""
        total = 0
        for pump in self.pumps.values():
            total += pump.pump(budget_ops=budget_ops)
        self._release()
        return total

    def _release(self) -> None:
        if not self.pumps:
            return
        slowest = min(p.next_op for p in self.pumps.values())
        self.tail.release_below(slowest)
        self._g_lag.set(
            max(0, self.replica.cdc_commit_min - slowest + 1)
        )

    def flush(self) -> None:
        """Shutdown: every consumer's cursor to its streamed head, every
        sink flushed."""
        for pump in self.pumps.values():
            pump.flush()

    def close(self) -> None:
        self.flush()
        for pump in self.pumps.values():
            pump.sink.close()

    def lag_ops(self) -> dict[str, int]:
        """Per-consumer distance from the finalized watermark (tests /
        the [stats] line)."""
        head = self.replica.cdc_commit_min
        return {
            name: max(0, head - p.next_op + 1)
            for name, p in self.pumps.items()
        }
