"""IngressGateway: session tracking + load shedding in front of the replica.

The gateway wraps the replica's network handler (the same seam for the
TCP bus and the in-process/simulated transports): non-request traffic
(consensus, repair, sync) passes through at the cost of one byte
compare; request frames go through per-session sequence tracking and
the credit regulator. A request the pipeline cannot absorb is answered
with a typed `Command.busy` reply echoing the client + request number —
the client keeps the same bytes in flight and resends after backoff
(vsr/client.py `busy`), instead of timing out against a silent drop.

Session table: one tiny record per LOGICAL session (client id), not per
connection — many sessions share one TCP connection (the bus aliases
reply routing by client id; io/message_bus.py "Session multiplexing").
The record is (conn, last_request): small enough that 10k+ sessions
are a few MB and admission stays O(1).

Retransmits are never shed: a request at-or-below the session's
last-admitted number is either still in the pipeline (the replica
dedups it) or already executed (the replica resends the cached reply)
— both are cheap, and shedding one would stall a client's reply
recovery behind its backoff.
"""

from __future__ import annotations

from tigerbeetle_tpu.io.message_bus import TCPMessageBus
from tigerbeetle_tpu.ingress.regulator import CreditRegulator
from tigerbeetle_tpu.latency import NULL_ANATOMY
from tigerbeetle_tpu.vsr.header import HEADER_SIZE, Command, Header

# peeked header fields, layout-pinned at import by io/message_bus.py
_CMD_OFF = TCPMessageBus._CMD_OFF
_CLIENT_OFF = TCPMessageBus._CLIENT_OFF
_REQUEST_OFF = TCPMessageBus._REQUEST_OFF
_OP_OFF = TCPMessageBus._OP_OFF
_CMD_REQUEST = int(Command.request)


class _Session:
    __slots__ = ("conn", "last_request")

    def __init__(self, conn=None, last_request: int = 0):
        self.conn = conn  # bus connection currently routing this session
        self.last_request = last_request  # highest ADMITTED request number


class IngressGateway:
    def __init__(self, network, replica, sessions_max: int = 0,
                 regulator: CreditRegulator | None = None):
        self.network = network
        self.replica = replica
        # 0 = unbounded here (the replica's clients_max eviction still
        # caps the replicated table; the gateway cap sheds BEFORE an
        # eviction storm instead of after)
        self.sessions_max = sessions_max
        self.regulator = regulator or CreditRegulator(
            replica, pool=getattr(network, "pool", None)
        )
        self.sessions: dict[int, _Session] = {}
        self._inner = None
        # latency anatomy bound once (one attr hop per frame instead of
        # two); harness replicas without one get the shared inert
        # instance
        self._latency = getattr(replica, "latency", None) or NULL_ANATOMY
        m = replica.metrics
        self._c_admitted = m.counter("ingress.admitted")
        self._c_shed = m.counter("ingress.shed")
        self._c_shed_sessions = m.counter("ingress.shed_sessions")
        self._c_retransmits = m.counter("ingress.retransmits")
        self._c_passthrough = m.counter("ingress.passthrough_backup")
        self._g_sessions = m.gauge("ingress.sessions")

    # -- install / uninstall (the handler-wrap seam) --

    def install(self) -> None:
        """Wrap the replica's attached handler. Call after replica.open()
        (the replica attaches at construction; open only recovers state).
        Also registers as the bus's ingress seam for session-alias and
        connection-close callbacks."""
        assert self._inner is None, "gateway already installed"
        handlers = self.network.handlers
        addr = self.replica.replica
        self._inner = handlers[addr]
        handlers[addr] = self.on_frame
        if hasattr(self.network, "ingress"):
            self.network.ingress = self
        self.replica.ingress_evict_hook = self.on_evict

    def uninstall(self) -> None:
        if self._inner is not None:
            self.network.handlers[self.replica.replica] = self._inner
            self._inner = None
            if getattr(self.network, "ingress", None) is self:
                self.network.ingress = None
            if self.replica.ingress_evict_hook is self.on_evict:
                self.replica.ingress_evict_hook = None

    # -- bus callbacks (TCP only; in-process transports never call) --

    def on_session(self, cid: int, conn) -> None:
        """The bus aliased `cid`'s reply routing to `conn` (first frame,
        or a reconnect taking over) — latest wins, like the alias."""
        sess = self.sessions.get(cid)
        if sess is not None:
            sess.conn = conn

    def on_evict(self, cid: int) -> None:
        """The replica evicted `cid` from its client table (register at
        clients_max). Track it: an evicted session on a still-open
        multiplexed connection would otherwise hold a table entry — and
        a sessions_max credit — until every session on that connection
        disconnects."""
        if self.sessions.pop(cid, None) is not None:
            self._g_sessions.set(len(self.sessions))

    def on_conn_close(self, conn) -> None:
        """Sessions routed over a closing connection leave the gateway
        table (re-admitted on reconnect); their replica client-table
        entries survive, so the session itself resumes where it was."""
        dropped = False
        for cid in getattr(conn, "sessions", ()):
            sess = self.sessions.get(cid)
            if sess is not None and sess.conn is conn:
                del self.sessions[cid]
                dropped = True
        if dropped:
            self._g_sessions.set(len(self.sessions))

    # -- the frame path --

    def on_frame(self, src, frame: bytes) -> None:
        if len(frame) < HEADER_SIZE or frame[_CMD_OFF] != _CMD_REQUEST:
            self._inner(src, frame)  # consensus/repair/sync: pass through
            return
        if not self.replica.is_primary:
            # Shed/busy interplay with client failover: the runtime's
            # timeout RE-TARGETS requests round-robin, so backups see a
            # spray of requests they will drop (not primary). Admitting
            # them would burn credits and grow this gateway's session
            # table from traffic it never serves; SHEDDING them would be
            # worse — a busy reply stamped with a stale view would tell
            # the client "alive, back off" about a replica that cannot
            # serve it, stalling failover behind the busy ladder. Pass
            # through untouched: the replica drops it, the client's
            # timeout walks on to the primary.
            self._c_passthrough.add()
            self._inner(src, frame)
            return
        cid = int.from_bytes(
            frame[_CLIENT_OFF : _CLIENT_OFF + 16], "little"
        )
        req = int.from_bytes(
            frame[_REQUEST_OFF : _REQUEST_OFF + 4], "little"
        )
        # Latency-anatomy arrival stamp (latency.py): the gateway is the
        # earliest point the process sees the request, so the sampled
        # request's ingress_admission leg starts HERE — covering gateway
        # admission plus the replica's dedup/backpressure checks. One
        # flag test per request frame while unsampled; a stamp consumed
        # by no record (this frame shed or deduped) goes stale and the
        # anatomy's freshness guard discards it.
        self._latency.arrive()
        sess = self.sessions.get(cid)
        if sess is None:
            # new logical session (its register — or the first frame the
            # gateway sees from a session established before install)
            if (
                self.sessions_max
                and len(self.sessions) >= self.sessions_max
                and not self._reclaim_dead()
            ):
                self._c_shed_sessions.add()
                self._shed(cid, req, frame[_OP_OFF])
                return
            if not self.regulator.try_admit():
                self._shed(cid, req, frame[_OP_OFF])
                return
            conns = getattr(self.network, "conns", None)
            self.sessions[cid] = _Session(
                conn=conns.get(cid) if conns is not None else None,
                last_request=req,
            )
            self._g_sessions.set(len(self.sessions))
            self._c_admitted.add()
            self._inner(src, frame)
            return
        if req <= sess.last_request:
            self._c_retransmits.add()
            self._inner(src, frame)  # never shed a retransmit
            return
        if not self.regulator.try_admit():
            self._shed(cid, req, frame[_OP_OFF])
            return
        sess.last_request = req
        self._c_admitted.add()
        self._inner(src, frame)

    def _reclaim_dead(self) -> bool:
        """O(1) insurance at the cap: if the OLDEST tracked session is no
        longer in the replica's client table (evicted before the gateway
        installed, or admitted over a transport that never reports conn
        closes), drop it and admit the newcomer in its place. One probe
        per full-table admission attempt — never a table scan."""
        oldest = next(iter(self.sessions), None)
        if oldest is None or oldest in self.replica.client_table:
            return False
        if (
            self.sessions[oldest].last_request == 0
            and self.replica.ingress_occupancy()[0]
        ):
            # absent from the client table, but its register (request 0)
            # may still be in the commit pipeline — not provably dead
            return False
        del self.sessions[oldest]
        return True

    def _shed(self, cid: int, req: int, operation: int) -> None:
        """Typed refusal: busy echoes the client + request (+ operation,
        for the client's own bookkeeping). A reply the pool cannot carry
        is dropped — the client's retry timeout still covers it."""
        self._c_shed.add()
        h = Header(
            command=int(Command.busy),
            client=cid,
            request=req,
            operation=operation,
        )
        # replica._send stamps replica/view/cluster + checksums — the
        # same wire discipline every other reply leaves with
        self.replica._send(cid, h)
