"""Credit-based admission control: O(1) per request.

The regulator's job is a yes/no per incoming request, fast enough to
sit in front of every frame at 10k+ sessions. Reading the pipeline
occupancy per request would already be O(1), but credits make the
common case one integer decrement with NO cross-object reads: one
occupancy read mints a batch of credits equal to the commit pipeline's
free capacity, and each admission spends one. When the batch is spent
the next request pays for a fresh occupancy read — so admissions track
the pipeline exactly (a minted batch fills the pipeline precisely to
its cap if nothing commits meanwhile, and a commit frees capacity the
next refill observes).

Two saturation signals gate a refill:

- `Replica.ingress_occupancy()` — quorum-pending pipeline entries plus
  the dispatched-but-unfinalized backlog beyond the steady async
  window, against the same cap `_on_request` backpressures at. The
  gateway sheds with a typed busy reply just BEFORE the replica would
  start dropping silently.
- the bus `MessagePool` budget — when the shared send budget is nearly
  exhausted the replica could commit but not reply; admitting more
  requests would turn reply-path backpressure into client timeouts, so
  the regulator holds admissions until the pool drains below the
  headroom line.
"""

from __future__ import annotations


class CreditRegulator:
    def __init__(self, replica, pool=None, pool_headroom: float = 0.25):
        self.replica = replica
        self.pool = pool  # bus MessagePool (None: no pool signal)
        self.pool_headroom = pool_headroom
        self._credits = 0
        self.refills = 0  # observability: occupancy reads paid

    def try_admit(self) -> bool:
        """One request's admission. Spends a credit, or mints a fresh
        batch from the pipeline's free capacity; False = shed (typed
        busy reply, the client retries with backoff)."""
        if self._credits > 0:
            self._credits -= 1
            return True
        used, cap = self.replica.ingress_occupancy()
        free = cap - used
        if free <= 0:
            return False
        pool = self.pool
        if (
            pool is not None
            and pool.used > pool.capacity * (1.0 - self.pool_headroom)
        ):
            return False  # reply budget nearly gone: replies first
        self.refills += 1
        self._credits = free - 1  # this admission spends the first
        return True

    def drain(self) -> None:
        """Drop minted credits (tests / a saturation flip must observe
        fresh occupancy immediately)."""
        self._credits = 0
