"""Ingress gateway: the session-oriented front door for 10k+ clients.

The commit pipeline behind the fuse window sustains hundreds of
thousands of durable tps, but a production ledger's first pipeline
stage is the NETWORK path ("Blockchain Machine" treats ingress as the
accelerator's stage 0) — and a front door sized for a dozen bench
sessions falls over at the first connect storm. This package is the
gateway layer between the transport and the replica:

- `gateway.IngressGateway`: the per-replica admission front door. It
  wraps the replica's message handler, tracks LOGICAL sessions (many
  sessions multiplex over one TCP connection — the bus aliases reply
  routing per client id, the gateway tracks per-session request
  sequence), and answers requests the pipeline cannot absorb with a
  typed `Command.busy` reply instead of letting them queue unboundedly
  or drop silently. The replica never blocks on ingress; a shed client
  backs off and resends the same bytes.
- `regulator.CreditRegulator`: O(1) credit-based admission fed by the
  commit pipeline's occupancy (`Replica.ingress_occupancy`, the fuse
  window + async-commit backlog) and the bus `MessagePool` budget. One
  occupancy read mints a batch of credits equal to the free capacity;
  per-request admission is a decrement (AT2's per-client-state-tiny-
  enough-that-admission-is-O(1) argument).
- `fanout.CdcFanoutHub`: one CDC tail feeding N consumer cursors. Each
  consumer owns its position, cursor and sink; the shared live window
  releases at the SLOWEST consumer's position (bounded — beyond the
  window a laggard falls back to WAL/AOF reads), so a throttled
  consumer pauses only itself. Closes the PR-4 one-cursor-per-sink
  limitation.

Transport-level defenses (accept-drain behind a deep listen backlog,
per-connection dispatch budgets against firehose peers, bounded recv
per turn against slow-loris trickles, write-queue caps that disconnect
wedged consumers, pool credit on close) live in io/message_bus.py; the
gateway is the policy layer above them.
"""

from tigerbeetle_tpu.ingress.fanout import CdcFanoutHub
from tigerbeetle_tpu.ingress.gateway import IngressGateway
from tigerbeetle_tpu.ingress.regulator import CreditRegulator

__all__ = ["CdcFanoutHub", "CreditRegulator", "IngressGateway"]
