"""StatsD metrics emitter (reference: src/statsd.zig:12 — UDP, fire and
forget, used by the benchmark's --statsd flag)."""

from __future__ import annotations

import socket


class StatsD:
    def __init__(self, host: str = "127.0.0.1", port: int = 8125,
                 prefix: str = "tigerbeetle_tpu"):
        self.addr = (host, port)
        self.prefix = prefix
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.setblocking(False)

    def _send(self, payload: str) -> None:
        try:
            self.sock.sendto(payload.encode(), self.addr)
        except OSError:
            pass  # metrics are best-effort

    def count(self, name: str, value: int = 1) -> None:
        self._send(f"{self.prefix}.{name}:{value}|c")

    def gauge(self, name: str, value: float) -> None:
        self._send(f"{self.prefix}.{name}:{value}|g")

    def timing(self, name: str, ms: float) -> None:
        self._send(f"{self.prefix}.{name}:{ms}|ms")

    def close(self) -> None:
        self.sock.close()
