"""StatsD metrics emitter (reference: src/statsd.zig:12 — UDP, fire and
forget, used by the benchmark's --statsd flag).

Two layers:

- `StatsD`: the raw socket. count/gauge/timing send one datagram each
  (kept for one-off emission and the existing tests); `send_batch` packs
  many metric lines into MTU-sized datagrams (newline-separated, the
  standard statsd multi-metric packet) — the reference's statsd.zig
  aggregates and flushes the same way rather than paying a syscall per
  metric.
- `StatsDEmitter`: periodic flush of a whole metrics registry
  (tigerbeetle_tpu/metrics.py): counters as deltas since the last flush,
  gauges as-is, histogram percentile snapshots as gauges — one batched
  send per flush interval instead of one packet per metric per tick.
"""

from __future__ import annotations

import socket

DEFAULT_HOST = "127.0.0.1"
DEFAULT_PORT = 8125
# Conservative UDP payload budget: fits any common MTU (1500 ethernet
# minus IP/UDP headers) without fragmentation.
MTU_PAYLOAD = 1400


def parse_addr(s: str) -> tuple[str, int]:
    """Parse a --statsd address. Accepts `host`, `:port`, and `host:port`
    (a bare host previously crashed on int("") after rpartition)."""
    host, sep, port = s.strip().rpartition(":")
    if not sep:  # bare host (no colon at all): rpartition put it in `port`
        return (port or DEFAULT_HOST, DEFAULT_PORT)
    return (host or DEFAULT_HOST, int(port) if port else DEFAULT_PORT)


class StatsD:
    def __init__(self, host: str = DEFAULT_HOST, port: int = DEFAULT_PORT,
                 prefix: str = "tigerbeetle_tpu"):
        self.addr = (host, port)
        self.prefix = prefix
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.setblocking(False)

    def _send(self, payload: str) -> None:
        try:
            self.sock.sendto(payload.encode(), self.addr)
        except OSError:
            pass  # metrics are best-effort

    def count(self, name: str, value: int = 1) -> None:
        self._send(f"{self.prefix}.{name}:{value}|c")

    def gauge(self, name: str, value: float) -> None:
        self._send(f"{self.prefix}.{name}:{value}|g")

    def timing(self, name: str, ms: float) -> None:
        self._send(f"{self.prefix}.{name}:{ms}|ms")

    def send_batch(self, lines: list[str]) -> int:
        """Pack metric lines into newline-separated datagrams, each at
        most MTU_PAYLOAD bytes. Returns the number of datagrams sent."""
        sent = 0
        buf: list[str] = []
        size = 0
        for line in lines:
            n = len(line) + (1 if buf else 0)
            if buf and size + n > MTU_PAYLOAD:
                self._send("\n".join(buf))
                sent += 1
                buf, size = [], 0
                n = len(line)
            buf.append(line)
            size += n
        if buf:
            self._send("\n".join(buf))
            sent += 1
        return sent

    def close(self) -> None:
        self.sock.close()


class StatsDEmitter:
    """Batched flush of a Metrics registry through one StatsD socket.

    Counters emit DELTAS since the previous flush (statsd `|c` semantics)
    and are skipped entirely when unchanged; gauges always emit; histogram
    snapshots emit p50/p95/p99/max as gauges under `<name>.<stat>` plus
    the observation-count delta as `<name>.count|c` — so a downstream
    aggregator can compute observation RATES, and a histogram that saw no
    new observations since the last flush costs no datagram bytes at all
    (an idle server used to re-emit every percentile every second)."""

    def __init__(self, statsd: StatsD, metrics):
        self.statsd = statsd
        self.metrics = metrics
        self._last: dict[str, float] = {}
        self._last_hist: dict[str, int] = {}

    def _lines(self) -> list[str]:
        snap = self.metrics.snapshot()
        prefix = self.statsd.prefix
        lines: list[str] = []
        for name, value in snap["counters"].items():
            delta = value - self._last.get(name, 0)
            if delta:
                self._last[name] = value
                lines.append(f"{prefix}.{name}:{delta}|c")
        for name, value in snap["gauges"].items():
            lines.append(f"{prefix}.{name}:{value}|g")
        for name, h in snap["histograms"].items():
            count = h.get("count", 0)
            delta = count - self._last_hist.get(name, 0)
            if not delta:
                continue  # nothing observed since the last flush
            self._last_hist[name] = count
            lines.append(f"{prefix}.{name}.count:{delta}|c")
            for stat in ("p50", "p95", "p99", "max"):
                lines.append(f"{prefix}.{name}.{stat}:{h[stat]}|g")
        return lines

    def flush(self) -> int:
        """One batched emission pass; returns datagrams sent."""
        return self.statsd.send_batch(self._lines())
