"""Python binding over the native C-ABI client (native/tb_client.cc).

The pattern the reference uses for all language bindings — one native
client library, typed wrappers per language (reference: src/clients/go,
java, dotnet, node over src/clients/c/tb_client.zig). This is the Python
instance: ctypes over tb_client.h, exposing typed Account/Transfer calls.
"""

from __future__ import annotations

import ctypes
import os

from tigerbeetle_tpu import native, types
from tigerbeetle_tpu.state_machine import decode_results, encode_ids
from tigerbeetle_tpu.types import Operation

MESSAGE_BODY_MAX = (1 << 20) - 128


class _TBClientHandle(ctypes.Structure):
    pass


def _lib():
    l = native.lib()  # builds/loads libtb_native.so (shared with checksum/io)
    if not hasattr(l, "_tb_client_bound"):
        l.tb_client_init.argtypes = [
            ctypes.POINTER(ctypes.POINTER(_TBClientHandle)),
            ctypes.c_char_p, ctypes.c_int, ctypes.c_uint32, ctypes.c_char_p,
        ]
        l.tb_client_init.restype = ctypes.c_int
        l.tb_client_request.argtypes = [
            ctypes.POINTER(_TBClientHandle), ctypes.c_uint8, ctypes.c_char_p,
            ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        l.tb_client_request.restype = ctypes.c_int
        l.tb_client_deinit.argtypes = [ctypes.POINTER(_TBClientHandle)]
        l.tb_client_deinit.restype = None
        l._tb_client_bound = True
    return l


class _TBPacket(ctypes.Structure):
    pass


_TBPacket._fields_ = [
    ("next", ctypes.POINTER(_TBPacket)),
    ("user_data", ctypes.c_void_p),
    ("operation", ctypes.c_uint8),
    ("status", ctypes.c_int32),
    ("data_size", ctypes.c_uint32),
    ("data", ctypes.c_void_p),
]

_COMPLETION_T = ctypes.CFUNCTYPE(
    None, ctypes.c_void_p, ctypes.POINTER(_TBPacket),
    ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
)


class _TBAsyncHandle(ctypes.Structure):
    pass


def _bind_async(l):
    if not hasattr(l, "_tb_async_bound"):
        l.tb_client_async_init.argtypes = [
            ctypes.POINTER(ctypes.POINTER(_TBAsyncHandle)),
            ctypes.c_char_p, ctypes.c_uint32, ctypes.c_char_p,
            ctypes.c_uint32, _COMPLETION_T, ctypes.c_void_p,
        ]
        l.tb_client_async_init.restype = ctypes.c_int
        l.tb_client_async_submit.argtypes = [
            ctypes.POINTER(_TBAsyncHandle), ctypes.POINTER(_TBPacket)
        ]
        l.tb_client_async_submit.restype = ctypes.c_int
        l.tb_client_async_deinit.argtypes = [ctypes.POINTER(_TBAsyncHandle)]
        l.tb_client_async_deinit.restype = None
        l._tb_async_bound = True
    return l


class AsyncNativeClient:
    """The async packet interface (reference: src/clients/c/tb_client/
    packet.zig completion model): submit() enqueues a request body and
    returns immediately; a pool of native session threads drives N requests
    in flight; each packet's reply bytes land in its Future.

    One process, many in-flight batches — the durable benchmark drives the
    full BASELINE protocol through this from a single client process."""

    def __init__(self, addresses: str, cluster: int = 0, sessions: int = 8,
                 client_id_base: bytes | None = None):
        from concurrent.futures import Future

        self._lib = _bind_async(_lib())
        self._handle = ctypes.POINTER(_TBAsyncHandle)()
        self._pending: dict[int, tuple] = {}  # packet addr -> (Future, keep)
        self._futures = Future  # for submit()

        def _on_complete(_ctx, pkt_ptr, reply_ptr, reply_len):
            pkt = pkt_ptr.contents
            key = ctypes.addressof(pkt)
            fut, _keep = self._pending.pop(key)
            if pkt.status != 0:
                fut.set_exception(
                    OSError(-pkt.status, os.strerror(-pkt.status))
                )
            else:
                fut.set_result(
                    ctypes.string_at(reply_ptr, reply_len) if reply_len else b""
                )

        self._cb = _COMPLETION_T(_on_complete)  # keep the thunk alive
        # sessions perturb byte 0 of the base id by +i: leave headroom
        cid = client_id_base or (b"\x01" + os.urandom(14) + b"\x01")
        rc = self._lib.tb_client_async_init(
            ctypes.byref(self._handle), addresses.encode(), cluster, cid,
            sessions, self._cb, None,
        )
        if rc != 0:
            raise OSError(-rc, os.strerror(-rc), addresses)

    def submit(self, operation: Operation, body: bytes):
        """Enqueue one request; returns a Future resolving to the reply
        body bytes (raises OSError on packet failure)."""
        fut = self._futures()
        pkt = _TBPacket()
        buf = ctypes.create_string_buffer(body, len(body))
        pkt.user_data = None
        pkt.operation = int(operation)
        pkt.data_size = len(body)
        pkt.data = ctypes.cast(buf, ctypes.c_void_p)
        # keep packet + body alive until completion (C owns no memory)
        self._pending[ctypes.addressof(pkt)] = (fut, (pkt, buf))
        rc = self._lib.tb_client_async_submit(self._handle, ctypes.byref(pkt))
        if rc != 0:
            self._pending.pop(ctypes.addressof(pkt))
            raise OSError(-rc, os.strerror(-rc), operation.name)
        return fut

    def close(self) -> None:
        if self._handle:
            self._lib.tb_client_async_deinit(self._handle)  # drains first
            self._handle = ctypes.POINTER(_TBAsyncHandle)()


class NativeClient:
    """A registered session against a running cluster, via the native lib."""

    def __init__(self, host: str, port: int = 0, cluster: int = 0,
                 client_id: bytes | None = None):
        """host: one "host" (with port arg) or a full address list
        "host:port[,host:port...]" — the client rotates across replicas."""
        self._lib = _lib()
        self._handle = ctypes.POINTER(_TBClientHandle)()
        cid = client_id or os.urandom(15) + b"\x01"  # nonzero u128
        addresses = host if ":" in host else f"{host}:{port}"
        rc = self._lib.tb_client_init(
            ctypes.byref(self._handle), addresses.encode(), 0, cluster, cid
        )
        if rc != 0:
            raise OSError(-rc, os.strerror(-rc), addresses)
        # reply buffer reused across requests (single in-flight by design)
        self._reply_buf = ctypes.create_string_buffer(MESSAGE_BODY_MAX)

    def _request(self, operation: Operation, body: bytes) -> bytes:
        out = self._reply_buf
        out_len = ctypes.c_uint64(0)
        rc = self._lib.tb_client_request(
            self._handle, int(operation), body, len(body), out,
            MESSAGE_BODY_MAX, ctypes.byref(out_len),
        )
        if rc != 0:
            raise OSError(-rc, os.strerror(-rc), operation.name)
        return out.raw[: out_len.value]

    # -- typed API (the binding surface) --

    def create_accounts(self, accounts: list[types.Account]):
        reply = self._request(
            Operation.create_accounts, types.accounts_to_np(accounts).tobytes()
        )
        return decode_results(reply, Operation.create_accounts)

    def create_transfers(self, transfers: list[types.Transfer]):
        reply = self._request(
            Operation.create_transfers,
            types.transfers_to_np(transfers).tobytes(),
        )
        return decode_results(reply, Operation.create_transfers)

    def lookup_accounts(self, ids: list[int]) -> list[types.Account]:
        import numpy as np

        reply = self._request(Operation.lookup_accounts, encode_ids(ids))
        rows = np.frombuffer(reply, dtype=types.ACCOUNT_DTYPE)
        return [types.Account.from_np(rows[i]) for i in range(len(rows))]

    def lookup_transfers(self, ids: list[int]) -> list[types.Transfer]:
        import numpy as np

        reply = self._request(Operation.lookup_transfers, encode_ids(ids))
        rows = np.frombuffer(reply, dtype=types.TRANSFER_DTYPE)
        return [types.Transfer.from_np(rows[i]) for i in range(len(rows))]

    def close(self) -> None:
        if self._handle:
            self._lib.tb_client_deinit(self._handle)
            self._handle = ctypes.POINTER(_TBClientHandle)()
