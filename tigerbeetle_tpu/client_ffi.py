"""Python binding over the native C-ABI client (native/tb_client.cc).

The pattern the reference uses for all language bindings — one native
client library, typed wrappers per language (reference: src/clients/go,
java, dotnet, node over src/clients/c/tb_client.zig). This is the Python
instance: ctypes over tb_client.h, exposing typed Account/Transfer calls.
"""

from __future__ import annotations

import ctypes
import os

from tigerbeetle_tpu import native, types
from tigerbeetle_tpu.state_machine import decode_results, encode_ids
from tigerbeetle_tpu.types import Operation

MESSAGE_BODY_MAX = (1 << 20) - 128


class _TBClientHandle(ctypes.Structure):
    pass


def _lib():
    l = native.lib()  # builds/loads libtb_native.so (shared with checksum/io)
    if not hasattr(l, "_tb_client_bound"):
        l.tb_client_init.argtypes = [
            ctypes.POINTER(ctypes.POINTER(_TBClientHandle)),
            ctypes.c_char_p, ctypes.c_int, ctypes.c_uint32, ctypes.c_char_p,
        ]
        l.tb_client_init.restype = ctypes.c_int
        l.tb_client_request.argtypes = [
            ctypes.POINTER(_TBClientHandle), ctypes.c_uint8, ctypes.c_char_p,
            ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        l.tb_client_request.restype = ctypes.c_int
        l.tb_client_deinit.argtypes = [ctypes.POINTER(_TBClientHandle)]
        l.tb_client_deinit.restype = None
        l._tb_client_bound = True
    return l


class NativeClient:
    """A registered session against a running cluster, via the native lib."""

    def __init__(self, host: str, port: int = 0, cluster: int = 0,
                 client_id: bytes | None = None):
        """host: one "host" (with port arg) or a full address list
        "host:port[,host:port...]" — the client rotates across replicas."""
        self._lib = _lib()
        self._handle = ctypes.POINTER(_TBClientHandle)()
        cid = client_id or os.urandom(15) + b"\x01"  # nonzero u128
        addresses = host if ":" in host else f"{host}:{port}"
        rc = self._lib.tb_client_init(
            ctypes.byref(self._handle), addresses.encode(), 0, cluster, cid
        )
        if rc != 0:
            raise OSError(-rc, os.strerror(-rc), addresses)
        # reply buffer reused across requests (single in-flight by design)
        self._reply_buf = ctypes.create_string_buffer(MESSAGE_BODY_MAX)

    def _request(self, operation: Operation, body: bytes) -> bytes:
        out = self._reply_buf
        out_len = ctypes.c_uint64(0)
        rc = self._lib.tb_client_request(
            self._handle, int(operation), body, len(body), out,
            MESSAGE_BODY_MAX, ctypes.byref(out_len),
        )
        if rc != 0:
            raise OSError(-rc, os.strerror(-rc), operation.name)
        return out.raw[: out_len.value]

    # -- typed API (the binding surface) --

    def create_accounts(self, accounts: list[types.Account]):
        reply = self._request(
            Operation.create_accounts, types.accounts_to_np(accounts).tobytes()
        )
        return decode_results(reply, Operation.create_accounts)

    def create_transfers(self, transfers: list[types.Transfer]):
        reply = self._request(
            Operation.create_transfers,
            types.transfers_to_np(transfers).tobytes(),
        )
        return decode_results(reply, Operation.create_transfers)

    def lookup_accounts(self, ids: list[int]) -> list[types.Account]:
        import numpy as np

        reply = self._request(Operation.lookup_accounts, encode_ids(ids))
        rows = np.frombuffer(reply, dtype=types.ACCOUNT_DTYPE)
        return [types.Account.from_np(rows[i]) for i in range(len(rows))]

    def lookup_transfers(self, ids: list[int]) -> list[types.Transfer]:
        import numpy as np

        reply = self._request(Operation.lookup_transfers, encode_ids(ids))
        rows = np.frombuffer(reply, dtype=types.TRANSFER_DTYPE)
        return [types.Transfer.from_np(rows[i]) for i in range(len(rows))]

    def close(self) -> None:
        if self._handle:
            self._lib.tb_client_deinit(self._handle)
            self._handle = ctypes.POINTER(_TBClientHandle)()
