"""The Time seam.

The reference injects Time as a comptime parameter so the simulator can run
the whole cluster on virtual ticks (reference: src/testing/time.zig;
composition src/tigerbeetle/main.zig:26-33). Same seam here: production
uses the OS clocks; tests/simulator use DeterministicTime advanced by the
event loop."""

from __future__ import annotations

import time as _time


class Time:
    def monotonic(self) -> int:
        """Monotonic nanoseconds (never goes backwards)."""
        raise NotImplementedError

    def realtime(self) -> int:
        """Wall-clock nanoseconds since epoch (may step)."""
        raise NotImplementedError

    def tick(self) -> None:
        """Advance one tick (no-op on real time)."""


class RealTime(Time):
    def monotonic(self) -> int:
        return _time.monotonic_ns()

    def realtime(self) -> int:
        return _time.time_ns()


class DeterministicTime(Time):
    """Virtual clock: one tick = tick_ns monotonic; realtime = epoch +
    monotonic + a fixed offset (per-replica offsets model clock skew,
    reference: src/testing/time.zig OffsetType)."""

    def __init__(self, tick_ns: int = 10_000_000, epoch: int = 1_600_000_000_000_000_000,
                 offset_ns: int = 0):
        self.tick_ns = tick_ns
        self.epoch = epoch
        self.offset_ns = offset_ns
        self.ticks = 0

    def monotonic(self) -> int:
        return self.ticks * self.tick_ns

    def realtime(self) -> int:
        return self.epoch + self.monotonic() + self.offset_ns

    def tick(self) -> None:
        self.ticks += 1
