"""The Network seam: message transport between replicas and clients.

The reference's MessageBus is a TCP mesh in production and a virtual
PacketSimulator under test, swapped at the same interface (reference:
src/message_bus.zig:21-22 vs src/testing/cluster/network.zig). Same seam
here: `Network.send(src, dst, data)` with delivery via registered handlers.

Addresses: replicas are ints 0..n-1; clients are their u128 client ids.
Messages are REAL wire bytes (128-byte Header + body) — everything crossing
this seam would survive a socket.

InProcessNetwork is the deterministic scripted transport (cluster tests):
messages queue in send order and `step()`/`run()` pump them one at a time;
`filters` may drop or hold messages (partitions, drops — the LinkFilter
analog, reference: src/vsr/replica_test.zig scripted networks)."""

from __future__ import annotations

from collections import deque
from typing import Callable

Address = int  # replica index (< 2^32) or client id (u128)
Handler = Callable[[Address, bytes], None]
Filter = Callable[[Address, Address, bytes], bool]  # True = deliver


class Network:
    def attach(self, addr: Address, handler: Handler) -> None:
        raise NotImplementedError

    def send(self, src: Address, dst: Address, data: bytes) -> None:
        raise NotImplementedError


class InProcessNetwork(Network):
    def __init__(self):
        self.handlers: dict[Address, Handler] = {}
        self.queue: deque[tuple[Address, Address, bytes]] = deque()
        self.filters: list[Filter] = []
        self.delivered = 0
        self.dropped = 0

    def attach(self, addr: Address, handler: Handler) -> None:
        self.handlers[addr] = handler

    def send(self, src: Address, dst: Address, data: bytes) -> None:
        self.queue.append((src, dst, bytes(data)))

    # -- pumping --

    def step(self) -> bool:
        """Deliver one queued message (or drop it per filters). Returns
        False when the queue is empty."""
        if not self.queue:
            return False
        src, dst, data = self.queue.popleft()
        for f in self.filters:
            if not f(src, dst, data):
                self.dropped += 1
                return True
        handler = self.handlers.get(dst)
        if handler is None:
            self.dropped += 1
            return True
        self.delivered += 1
        handler(src, data)
        return True

    def run(self, limit: int = 100_000) -> int:
        """Pump until quiescent. Returns messages processed."""
        n = 0
        while self.step():
            n += 1
            if n >= limit:
                raise RuntimeError("network did not quiesce (livelock?)")
        return n


class LinkControl:
    """Scripted, fully deterministic link faults over InProcessNetwork
    (the client-runtime tests' fault dial): drop or HOLD messages
    matching a (src, dst) pattern — held messages are captured in order
    and re-injected by release(), modeling a delayed/duplicated delivery
    with an exact interleaving (no randomness; the seeded chaos lives in
    PacketSimulator)."""

    def __init__(self, network: InProcessNetwork):
        self.network = network
        self.rules: list[dict] = []
        self.held: list[tuple[Address, Address, bytes]] = []
        network.filters.append(self._filter)

    def _match(self, rule: dict, src: Address, dst: Address) -> bool:
        return (
            (rule["src"] is None or rule["src"] == src)
            and (rule["dst"] is None or rule["dst"] == dst)
        )

    def _filter(self, src: Address, dst: Address, data: bytes) -> bool:
        for rule in self.rules:
            if rule["remaining"] == 0 or not self._match(rule, src, dst):
                continue
            if rule["remaining"] > 0:
                rule["remaining"] -= 1
            if rule["mode"] == "hold":
                self.held.append((src, dst, data))
            return False
        return True

    def drop(self, src: Address | None = None, dst: Address | None = None,
             count: int = -1) -> dict:
        """Drop messages matching (src, dst); count<0 = until clear()."""
        rule = {"src": src, "dst": dst, "mode": "drop", "remaining": count}
        self.rules.append(rule)
        return rule

    def hold(self, src: Address | None = None, dst: Address | None = None,
             count: int = -1) -> dict:
        """Capture matching messages instead of delivering them; they
        re-enter the queue (in capture order) at release()."""
        rule = {"src": src, "dst": dst, "mode": "hold", "remaining": count}
        self.rules.append(rule)
        return rule

    def clear(self) -> None:
        self.rules.clear()

    def release(self, duplicate: int = 1) -> int:
        """Re-inject every held message `duplicate` times (1 = plain
        delayed delivery; 2 = delayed + duplicated — the stale-frame
        storms a healed link replays). Active rules still apply to the
        released copies (clear() first for a clean heal). Returns
        messages re-injected."""
        held, self.held = self.held, []
        n = 0
        for src, dst, data in held:
            for _ in range(duplicate):
                self.network.queue.append((src, dst, data))
                n += 1
        return n
