"""The Storage seam: zoned, durable sector IO.

This is the dependency-injection boundary the whole test strategy hangs on
(reference: src/storage.zig production vs src/testing/storage.zig fake,
injected comptime at src/tigerbeetle/main.zig:26-33; SURVEY.md §4 takeaway
"replicate the seam, not the files"). Everything above — journal,
superblock, grid, checkpoint — talks to this interface only, so the
deterministic simulator swaps in MemoryStorage (with per-zone fault
injection) with zero changes to the layers above.

Zones mirror the reference's disk layout (reference: src/vsr.zig:59-108):
superblock | wal_headers | wal_prepares | client_replies | grid.
"""

from __future__ import annotations

import enum
import os

from tigerbeetle_tpu.constants import ConfigCluster, DEFAULT_CLUSTER

SECTOR_SIZE = 4096


class Zone(enum.Enum):
    superblock = 0
    wal_headers = 1
    wal_prepares = 2
    client_replies = 3
    grid = 4


class ZoneLayout:
    """Byte offsets/sizes of each zone for a cluster config."""

    SUPERBLOCK_COPIES = 4
    SUPERBLOCK_COPY_SIZE = 64 * 1024  # header sector + trailers, padded

    def __init__(self, cluster: ConfigCluster = DEFAULT_CLUSTER,
                 grid_size: int = 64 * 1024 * 1024,
                 forest_blocks: int = 0):
        slot_count = cluster.journal_slot_count
        msg_max = cluster.message_size_max
        # The grid zone partitions as: two ping-pong snapshot areas | the
        # LSM forest's block area (`forest_blocks` 128 KiB blocks, for the
        # spill backing store — 0 when the ledger is HBM-only).
        self.forest_blocks = forest_blocks
        forest_size = forest_blocks * cluster.block_size
        assert forest_size < grid_size, "forest larger than the grid zone"
        self.snapshot_area_size = (grid_size - forest_size) // 2 // 4096 * 4096
        self.forest_offset = 2 * self.snapshot_area_size
        self.sizes = {
            Zone.superblock: self.SUPERBLOCK_COPIES * self.SUPERBLOCK_COPY_SIZE,
            Zone.wal_headers: _sector_ceil(slot_count * 128),
            Zone.wal_prepares: slot_count * msg_max,
            Zone.client_replies: cluster.reply_slot_count * msg_max,
            Zone.grid: grid_size,
        }
        self.starts = {}
        off = 0
        for z in Zone:
            self.starts[z] = off
            off += self.sizes[z]
        self.total_size = off

    def offset(self, zone: Zone, offset_logical: int) -> int:
        assert 0 <= offset_logical < self.sizes[zone], (zone, offset_logical)
        return self.starts[zone] + offset_logical


def _sector_ceil(n: int) -> int:
    return (n + SECTOR_SIZE - 1) // SECTOR_SIZE * SECTOR_SIZE


class Storage:
    """Interface: durable zoned IO. Writes are durable when the call returns
    (the file backend opens O_DSYNC / fdatasyncs)."""

    layout: ZoneLayout

    def read(self, zone: Zone, offset: int, size: int) -> bytes:
        raise NotImplementedError

    def write(self, zone: Zone, offset: int, data: bytes) -> None:
        raise NotImplementedError

    def write_lazy(self, zone: Zone, offset: int, data: bytes) -> None:
        """Buffered write: durable only after the next sync(). For data
        whose loss is tolerated by a checksum-validated read path (client
        reply slots) — an O_DSYNC flush per reply would contend with the
        WAL's flushes for the device (measured ~2 ms each, and far worse
        under concurrent 1 MiB prepare writes)."""
        self.write(zone, offset, data)

    def sync(self) -> None:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


class FileStorage(Storage):
    """Production path: the native C++ sector IO (native/storage.cc —
    O_DIRECT+O_DSYNC with buffered fallback; reference: src/storage.zig)."""

    def __init__(self, path: str, layout: ZoneLayout, create: bool = False):
        from tigerbeetle_tpu import native

        self.layout = layout
        self.path = path
        self._lib = native.lib()
        fd = self._lib.tb_storage_open(
            path.encode(), layout.total_size, 1 if create else 0
        )
        if fd < 0:
            raise OSError(-fd, os.strerror(-fd), path)
        self.fd = fd
        # Buffered second descriptor for write_lazy (no O_DSYNC): reply-slot
        # writes ride the page cache; sync() fdatasyncs it.
        self._lazy_fd = os.open(path, os.O_RDWR)

    def read(self, zone: Zone, offset: int, size: int) -> bytes:
        import ctypes

        buf = ctypes.create_string_buffer(size)
        rc = self._lib.tb_storage_read(
            self.fd, self.layout.offset(zone, offset), buf, size
        )
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))
        return buf.raw

    def write(self, zone: Zone, offset: int, data: bytes) -> None:
        rc = self._lib.tb_storage_write(
            self.fd, self.layout.offset(zone, offset), bytes(data), len(data)
        )
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))

    def write_lazy(self, zone: Zone, offset: int, data: bytes) -> None:
        os.pwrite(self._lazy_fd, data, self.layout.offset(zone, offset))

    def sync(self) -> None:
        os.fdatasync(self._lazy_fd)  # lazy writes become durable here
        rc = self._lib.tb_storage_sync(self.fd)
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))

    def close(self) -> None:
        if self.fd >= 0:
            self._lib.tb_storage_close(self.fd)
            self.fd = -1
            os.close(self._lazy_fd)


class MemoryStorage(Storage):
    """Deterministic in-memory fake (reference: src/testing/storage.zig).

    Durability contract matches the production backend: a write is durable
    when the call returns (FileStorage opens O_DSYNC). Fault injection:
    `fault(zone, offset, size)` flips bytes so checksums fail — the
    simulator drives this per its fault atlas. `crash()` models power loss
    DURING the single in-flight write: the LAST write (only) is torn,
    keeping or reverting each of its sectors independently (seeded). It
    must not drop earlier acknowledged writes — the production device
    cannot."""

    def __init__(self, layout: ZoneLayout, seed: int = 0):
        import random

        self.layout = layout
        self.data = bytearray(layout.total_size)
        self.rng = random.Random(seed)
        self._last_write: tuple[int, bytes] | None = None  # (abs, old bytes)
        self.reads = 0
        self.writes = 0
        # Optional per-read observer (zone, offset, size) — the simulator's
        # latency/IO-accounting injection point. Lives on the Storage seam
        # so the layers above stay untouched: a hook that sleeps models a
        # slow medium, a hook that records the calling context proves which
        # loop paid for the read (reference: src/testing/storage.zig models
        # read/write latency inside the fake, not the callers).
        self.read_hook = None

    def read(self, zone: Zone, offset: int, size: int) -> bytes:
        self.reads += 1
        if self.read_hook is not None:
            self.read_hook(zone, offset, size)
        start = self.layout.offset(zone, offset)
        return bytes(self.data[start : start + size])

    def write(self, zone: Zone, offset: int, data: bytes) -> None:
        self.writes += 1
        start = self.layout.offset(zone, offset)
        self._last_write = (start, bytes(self.data[start : start + len(data)]))
        self.data[start : start + len(data)] = data

    def sync(self) -> None:
        self._last_write = None  # a sync barrier: nothing in flight

    def close(self) -> None:
        pass

    # -- fault injection --

    def fault(self, zone: Zone, offset: int, size: int = SECTOR_SIZE) -> None:
        start = self.layout.offset(zone, offset)
        for i in range(start, min(start + size, len(self.data))):
            self.data[i] ^= 0xFF

    def crash(self) -> None:
        """Tear the single in-flight write: each of its sectors is
        independently kept or reverted (seeded)."""
        if self._last_write is None:
            return
        start, old = self._last_write
        for s in range(0, len(old), SECTOR_SIZE):
            if self.rng.random() < 0.5:  # this sector's write is lost
                end = min(s + SECTOR_SIZE, len(old))
                self.data[start + s : start + end] = old[s:end]
        self._last_write = None
