"""Production transport: the TCP message bus.

The reference's MessageBus (reference: src/message_bus.zig:24-70): replicas
listen on configured addresses and connect to each other; clients connect
in; messages are 128-byte-Header-framed (size from the header, checksums
validated by the receiver), with per-connection buffers and reconnect.

This implements the same Network seam as the in-process fakes, so the
Replica and Client run unchanged over real sockets. Non-blocking sockets
pumped by the process event loop (`pump()` ~ the reference's io.run_for_ns
tick, reference: src/tigerbeetle/main.zig start loop).

Replica-to-replica links: the replica with the LOWER index connects, the
higher accepts (a deterministic direction avoids duplicate links). Client
links: clients connect in; the bus learns the client id from the first
frame and routes replies back over the same connection.
"""

from __future__ import annotations

import errno
import selectors
import socket
import time as _time

from tigerbeetle_tpu.io.network import Address, Handler, Network
from tigerbeetle_tpu.metrics import NULL_METRICS
from tigerbeetle_tpu.tracer import NULL_TRACER
from tigerbeetle_tpu.vsr.header import HEADER_SIZE, Header

MESSAGE_SIZE_MAX_DEFAULT = 1 << 20


class MessagePool:
    """Fixed send-buffer accounting (reference: src/message_pool.zig:18-41
    — the pool is sized exactly from worst-case concurrent use, and
    exhaustion is BACKPRESSURE, not allocation): sends that would exceed
    the budget are dropped, which is safe for every VSR message class
    (the protocol retransmits on its timeouts)."""

    def __init__(self, messages_max: int = 64,
                 message_size_max: int = MESSAGE_SIZE_MAX_DEFAULT):
        self.capacity = messages_max * message_size_max
        self.used = 0
        self.dropped = 0  # observability: sends refused at the budget

    def try_charge(self, n: int) -> bool:
        if self.used + n > self.capacity:
            self.dropped += 1
            return False
        self.used += n
        return True

    def credit(self, n: int) -> None:
        self.used -= n
        assert self.used >= 0


class _Conn:
    __slots__ = ("sock", "peer", "connected", "rbuf", "roff", "wbuf")

    def __init__(self, sock: socket.socket, peer: Address | None = None,
                 connected: bool = True):
        self.sock = sock
        self.peer = peer  # replica index / client id once known
        self.connected = connected  # False while a non-blocking dial pends
        self.rbuf = bytearray()
        self.roff = 0  # consumed-frame offset into rbuf (compacted per turn)
        self.wbuf = bytearray()


class TCPMessageBus(Network):
    # observability seams (re-pointed by the composition root; defaults
    # are the zero-cost no-op backends)
    metrics = NULL_METRICS
    tracer = NULL_TRACER

    def __init__(
        self,
        addresses: list[tuple[str, int]],
        own_address: Address,
        listen: bool = False,
        message_size_max: int = MESSAGE_SIZE_MAX_DEFAULT,
        messages_max: int = 64,
    ):
        """addresses: replica index -> (host, port). own_address: our
        replica index, or our client id (clients don't listen)."""
        self.addresses = addresses
        self.own = own_address
        self.message_size_max = message_size_max
        self.pool = MessagePool(messages_max, message_size_max)
        # Per-connection send cap: one wedged peer (open socket, never
        # reads -> EAGAIN forever) must not consume the SHARED pool and
        # starve sends to the healthy quorum (the reference bounds per-
        # connection send queues the same way, src/message_bus.zig:24-70).
        self.conn_send_max = max(
            2, messages_max // max(2, len(addresses))
        ) * message_size_max
        self.sel = selectors.DefaultSelector()
        self.handlers: dict[Address, Handler] = {}
        self.conns: dict[Address, _Conn] = {}  # peer -> connection
        self.listener: socket.socket | None = None
        if listen:
            host, port = addresses[own_address]
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind((host, port))
            s.listen(64)
            s.setblocking(False)
            self.listener = s
            self.sel.register(s, selectors.EVENT_READ, ("accept", None))

    # -- Network seam --

    def attach(self, addr: Address, handler: Handler) -> None:
        self.handlers[addr] = handler

    # Sends below this wbuf level defer their socket write to the pump
    # turn's flush: a window of replies coalesces into ONE send syscall
    # (and one TCP segment burst) instead of one per 128-byte reply — and
    # the clients' next requests then arrive together, which is what feeds
    # the replica's group-commit fusion.
    FLUSH_EAGER = 1 << 17

    def send(self, src: Address, dst: Address, data: bytes) -> None:
        conn = self.conns.get(dst)
        if conn is None:
            if dst < len(self.addresses):
                conn = self._connect(dst)
            if conn is None:
                return  # unreachable peer: VSR retransmits cover the loss
        if len(conn.wbuf) + len(data) > self.conn_send_max:
            self.pool.dropped += 1
            return  # this peer is wedged: drop for IT, not for everyone
        if not self.pool.try_charge(len(data)):
            return  # pool exhausted: backpressure — VSR retransmits
        conn.wbuf += data
        if len(conn.wbuf) >= self.FLUSH_EAGER:
            self._flush(conn)  # large payloads start on the wire now

    def flush_pending(self) -> None:
        """Flush every connection's buffered sends (one syscall per conn
        per turn). pump() calls this on entry (so bytes queued between
        pumps never wait out a blocking select) and on exit (so sends
        queued by this turn's handlers leave with it)."""
        pending = [c for c in self.conns.values() if c.wbuf]
        if not pending:
            return
        self.metrics.counter("bus.flushes").add()
        with self.tracer.span("bus.flush", conns=len(pending)):
            for conn in pending:
                self._flush(conn)

    # -- connections --

    def _connect(self, replica: int) -> _Conn | None:
        # NON-BLOCKING dial: a blocked peer must never stall the event loop
        # (consensus for the live quorum would freeze for the TCP timeout).
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setblocking(False)
        try:
            rc = s.connect_ex(self.addresses[replica])
        except OSError:
            s.close()
            return None
        if rc not in (0, errno.EINPROGRESS, errno.EWOULDBLOCK):
            s.close()
            return None
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn = _Conn(s, peer=replica, connected=(rc == 0))
        self.conns[replica] = conn
        self.sel.register(
            s, selectors.EVENT_READ | selectors.EVENT_WRITE, ("conn", conn)
        )
        # identify ourselves so the acceptor can route replies (clients in
        # the u128 `client` field; replicas in the u8 `replica` field)
        hello = Header()
        if self.own < len(self.addresses):
            hello.replica = self.own
        else:
            hello.client = self.own
        hello.set_checksum_body(b"")
        hello.set_checksum()
        frame = hello.to_bytes()
        self.pool.used += len(frame)  # mandatory frame: charge unconditionally
        conn.wbuf += frame
        self._flush(conn)
        return conn

    def _accept(self) -> None:
        assert self.listener is not None
        try:
            s, _addr = self.listener.accept()
        except OSError:
            return
        s.setblocking(False)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn = _Conn(s)
        self.sel.register(s, selectors.EVENT_READ, ("conn", conn))

    def _close(self, conn: _Conn) -> None:
        try:
            self.sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        conn.sock.close()
        self.pool.credit(len(conn.wbuf))  # unsent bytes return to the pool
        conn.wbuf.clear()
        if conn.peer is not None and self.conns.get(conn.peer) is conn:
            del self.conns[conn.peer]

    def _flush(self, conn: _Conn) -> None:
        if not conn.connected:
            return  # dial still in progress; flushed on writability
        while conn.wbuf:
            try:
                n = conn.sock.send(conn.wbuf)
            except OSError as e:
                if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                    return
                self._close(conn)
                return
            if n <= 0:
                return
            del conn.wbuf[:n]
            self.pool.credit(n)
            self.metrics.counter("bus.tx_bytes").add(n)

    # -- pumping --

    def pump(self, timeout: float = 0.01) -> int:
        """One event-loop turn: accept/read/dispatch. Returns frames
        dispatched."""
        dispatched = 0
        t0 = _time.perf_counter_ns() if self.metrics.enabled else 0
        self.flush_pending()  # deferred sends must not wait out the select
        for key, mask in self.sel.select(timeout):
            kind, conn = key.data
            if kind == "accept":
                self._accept()
                continue
            if mask & selectors.EVENT_WRITE and not conn.connected:
                # pending dial resolved: success or failure
                err = conn.sock.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
                if err != 0:
                    self._close(conn)
                    continue
                conn.connected = True
                self.sel.modify(
                    conn.sock, selectors.EVENT_READ, ("conn", conn)
                )
                self._flush(conn)
            if not (mask & selectors.EVENT_READ):
                continue
            # Drain the socket buffer in one turn (a 1 MiB batch frame
            # spans many TCP segments; one recv per select round would cap
            # ingest at 64 KiB per event-loop turn). Bounded so one
            # firehose peer can't starve the rest of the loop. On FIN or
            # error, buffered frames STILL dispatch before the close —
            # a one-shot client may send its request and close.
            closing = False
            for _ in range(64):
                try:
                    chunk = conn.sock.recv(1 << 18)
                except OSError as e:
                    if e.errno not in (errno.EAGAIN, errno.EWOULDBLOCK):
                        closing = True
                    break
                if not chunk:
                    closing = True
                    break
                conn.rbuf += chunk
                if len(chunk) < (1 << 18):
                    break
            dispatched += self._drain(conn)
            if closing:
                self._close(conn)
        self.flush_pending()  # this turn's handler sends leave with it
        if dispatched and t0:
            # only turns that dispatched frames: idle selects would bury
            # the signal (and cost a histogram write per quiet turn)
            self.metrics.counter("bus.frames").add(dispatched)
            self.metrics.histogram("bus.pump_us").observe(
                (_time.perf_counter_ns() - t0) / 1000.0
            )
        return dispatched

    # byte offset of the header's size u32: five u128s (80) + four u32s
    # (16) + three u64s (24); cross-checked against Header at import
    _SIZE_OFF = 120

    def _drain(self, conn: _Conn) -> int:
        n = 0
        buf = conn.rbuf
        # frame-parse span: only when there is at least one parseable
        # frame AND tracing is on (pump calls _drain for every readable
        # conn; empty passes must stay free)
        tok = (
            self.tracer.start("bus.frame_parse")
            if self.tracer.enabled and len(buf) - conn.roff >= HEADER_SIZE
            else 0
        )
        mv = memoryview(buf)
        try:
            while len(buf) - conn.roff >= HEADER_SIZE:
                # framing needs only the size field — the full header
                # parse (and checksum) belongs to the handler; parsing it
                # here too would double the per-frame header cost
                o = conn.roff + self._SIZE_OFF
                size = int.from_bytes(mv[o : o + 4], "little")
                if size < HEADER_SIZE or size > self.message_size_max:
                    mv.release()
                    self._close(conn)  # corrupt framing: drop the conn
                    return n
                if len(buf) - conn.roff < size:
                    break
                frame = bytes(mv[conn.roff : conn.roff + size])
                conn.roff += size
                if conn.peer is None:
                    # first frame identifies the peer (hello or any
                    # message: the client field for clients, replica for
                    # replicas)
                    header = Header.from_bytes(frame[:HEADER_SIZE])
                    if not header.valid_checksum():
                        mv.release()
                        self._close(conn)
                        return n
                    peer = header.client if header.client else header.replica
                    conn.peer = peer
                    # Simultaneous dials create two links; keep the FIRST
                    # as canonical for sends (an overwrite would orphan
                    # its buffered partial frames) — this one stays
                    # readable.
                    if peer not in self.conns:
                        self.conns[peer] = conn
                    if size == HEADER_SIZE and header.command == 0:
                        continue  # pure hello: consume
                handler = self.handlers.get(self.own)
                if handler is not None:
                    handler(conn.peer, frame)
                    n += 1
        finally:
            mv.release()
            if tok:
                self.tracer.stop(tok)
        # compact ONCE per turn (a del per frame moved the whole tail —
        # O(bytes) per 1 MiB batch frame — on every message)
        if conn.roff:
            if conn.roff == len(buf):
                buf.clear()
            else:
                del buf[: conn.roff]
            conn.roff = 0
        return n


# the framing fast path peeks the size field without parsing the header —
# pin the offset against the Header layout so it can never drift silently
assert (
    int.from_bytes(
        Header(size=0x0BADF00D).to_bytes()[
            TCPMessageBus._SIZE_OFF : TCPMessageBus._SIZE_OFF + 4
        ],
        "little",
    )
    == 0x0BADF00D
)
