"""Production transport: the TCP message bus.

The reference's MessageBus (reference: src/message_bus.zig:24-70): replicas
listen on configured addresses and connect to each other; clients connect
in; messages are 128-byte-Header-framed (size from the header, checksums
validated by the receiver), with per-connection buffers and reconnect.

This implements the same Network seam as the in-process fakes, so the
Replica and Client run unchanged over real sockets. Non-blocking sockets
pumped by the process event loop (`pump()` ~ the reference's io.run_for_ns
tick, reference: src/tigerbeetle/main.zig start loop).

Replica-to-replica links: the replica with the LOWER index connects, the
higher accepts (a deterministic direction avoids duplicate links). Client
links: clients connect in; the bus learns the client id from the first
frame and routes replies back over the same connection.

Ingress extensions (tigerbeetle_tpu/ingress — the 10k-session front door):

- **Session multiplexing**: every request frame's client id is aliased to
  the connection it arrived on, so many logical sessions share one TCP
  connection and replies route per-session (`conns[client_id] -> conn`).
  The one-connection-per-client path is the degenerate single-session
  case (the alias equals the connection's hello peer). Aliases are
  latest-wins: a session reconnecting on a new connection takes its
  routing with it.
- **Fair pumping**: frames dispatched per connection per pump turn are
  bounded by `dispatch_budget`; leftovers stay buffered and the
  connection joins the hot list, drained FIRST next turn — one firehose
  peer cannot starve the rest of the loop. A trickling (slow-loris) peer
  never forms a frame and costs one bounded recv per readiness event.
- **Accept drain**: one readiness event accepts up to `accept_budget`
  pending connections behind a configurable `listen_backlog` — a connect
  storm of hundreds no longer lands one accept per select round.
- **Typed shed outcomes**: `send()` returns "sent" | "shed_conn" |
  "shed_pool" | "unreachable" and counts refusals into the ingress.*
  metrics instead of dropping silently; pool budget held by a closing
  connection is always credited back (churned clients cannot leak it).
- **Slow-peer defense**: a CLIENT connection whose send queue stays at
  its cap (open socket, never reads) accumulates strikes and is
  disconnected after `wedged_strikes_max` consecutive refusals —
  replica links are exempt (VSR owns their retry discipline).
- **Reconnect with backoff**: a lost or refused dial arms a per-replica
  backoff (50ms doubling to 2s, reset on success); sends inside the
  window return "unreachable" without burning a dial, the first send
  after it re-dials. Reconnection is LAZY — the retry that triggers the
  send is the client runtime's timeout (vsr/client.py) or VSR's own
  retransmits, so a restarted replica's clients re-attach without any
  driver code. Multiplexed (demux) sessions re-alias on the new
  connection automatically: the server re-learns each session's routing
  from the first request (or client ping) frame it sends there.
"""

from __future__ import annotations

import errno
import selectors
import socket
import time as _time

from tigerbeetle_tpu.io.network import Address, Handler, Network
from tigerbeetle_tpu.metrics import NULL_METRICS
from tigerbeetle_tpu.tracer import NULL_TRACER
from tigerbeetle_tpu.vsr.header import HEADER_SIZE, Command, Header, trace_id

MESSAGE_SIZE_MAX_DEFAULT = 1 << 20


class MessagePool:
    """Fixed send-buffer accounting (reference: src/message_pool.zig:18-41
    — the pool is sized exactly from worst-case concurrent use, and
    exhaustion is BACKPRESSURE, not allocation): sends that would exceed
    the budget are refused, which is safe for every VSR message class
    (the protocol retransmits on its timeouts). Exhaustion is a TYPED
    outcome (the bus counts it in ingress.shed_pool and its send()
    returns "shed_pool"), never a silent drop."""

    def __init__(self, messages_max: int = 64,
                 message_size_max: int = MESSAGE_SIZE_MAX_DEFAULT):
        self.capacity = messages_max * message_size_max
        self.used = 0
        self.dropped = 0  # observability: sends refused at the budget

    def try_charge(self, n: int) -> bool:
        if self.used + n > self.capacity:
            self.dropped += 1
            return False
        self.used += n
        return True

    def credit(self, n: int) -> None:
        self.used -= n
        assert self.used >= 0


class _Conn:
    __slots__ = (
        "sock", "peer", "connected", "rbuf", "roff", "wbuf",
        "sessions", "strikes", "pending_traces", "pending_lat",
    )

    def __init__(self, sock: socket.socket, peer: Address | None = None,
                 connected: bool = True):
        self.sock = sock
        self.peer = peer  # replica index / client id once known
        self.connected = connected  # False while a non-blocking dial pends
        self.rbuf = bytearray()
        self.roff = 0  # consumed-frame offset into rbuf (compacted per turn)
        self.wbuf = bytearray()
        # client ids whose reply routing aliases to this connection
        # (session multiplexing; empty for replica links)
        self.sessions: set[Address] = set()
        # consecutive sends refused at the per-connection cap: the
        # wedged-consumer disconnect counter (reset on flush progress)
        self.strikes = 0
        # tracing only: trace ids of reply frames queued in wbuf and not
        # yet flushed — PER CONNECTION, so a flush span is tagged with
        # exactly the replies that connection's write carried
        self.pending_traces: list[int] = []
        # latency-anatomy tokens of sampled replies queued in wbuf: the
        # flush that writes this conn finishes their records (the
        # reply_egress leg ends at the first socket write)
        self.pending_lat: list[int] = []


class TCPMessageBus(Network):
    # observability seams (re-pointed by the composition root; defaults
    # are the zero-cost no-op backends). `metrics` is a property so a
    # re-point rebinds the hot-path counters ONCE — per-event registry
    # lookups would tax exactly the overload paths (shed, accept storm)
    # the counters exist to observe.
    tracer = NULL_TRACER
    _metrics = NULL_METRICS
    # per-request latency anatomy (latency.py LatencyAnatomy), installed
    # by the composition root next to `defer_egress = True`: the replica
    # parks each sampled reply's record in `latency.pending_egress`
    # keyed by (client, context), send() claims it for the connection
    # that queues the reply frame, and the flush that writes the conn
    # closes the record (reply_egress = finalize -> first socket write)
    latency = None

    @property
    def metrics(self):
        return self._metrics

    @metrics.setter
    def metrics(self, m):
        self._metrics = m
        self._c_shed_conn = m.counter("ingress.shed_conn")
        self._c_disconnect_wedged = m.counter("ingress.disconnect_wedged")
        self._c_shed_pool = m.counter("ingress.shed_pool")
        self._c_accepts = m.counter("ingress.accepts")
        self._c_flushes = m.counter("bus.flushes")
        self._c_tx_bytes = m.counter("bus.tx_bytes")
        self._c_frames = m.counter("bus.frames")
        self._c_reconnects = m.counter("bus.reconnects")
        self._c_dial_failures = m.counter("bus.dial_failures")

    def __init__(
        self,
        addresses: list[tuple[str, int]],
        own_address: Address,
        listen: bool = False,
        message_size_max: int = MESSAGE_SIZE_MAX_DEFAULT,
        messages_max: int = 64,
        listen_backlog: int = 1024,
        accept_budget: int = 256,
        dispatch_budget: int = 256,
        wedged_strikes_max: int = 512,
        demux: bool = False,
    ):
        """addresses: replica index -> (host, port). own_address: our
        replica index, or our client id (clients don't listen).

        demux=True (client-side session multiplexing): inbound frames
        dispatch to the handler attached at the frame's CLIENT id, so N
        logical sessions' Clients share this one bus/connection — each
        attaches at its own id and sees only its own replies. The
        default routes everything to handlers[own] (one session per
        bus, the pre-ingress behavior)."""
        self.metrics = self._metrics  # bind the no-op counters until re-pointed
        self.addresses = addresses
        self.own = own_address
        self.demux = demux
        self.message_size_max = message_size_max
        self.pool = MessagePool(messages_max, message_size_max)
        # Per-connection send cap: one wedged peer (open socket, never
        # reads -> EAGAIN forever) must not consume the SHARED pool and
        # starve sends to the healthy quorum (the reference bounds per-
        # connection send queues the same way, src/message_bus.zig:24-70).
        self.conn_send_max = max(
            2, messages_max // max(2, len(addresses))
        ) * message_size_max
        self.accept_budget = accept_budget
        self.dispatch_budget = dispatch_budget
        self.wedged_strikes_max = wedged_strikes_max
        self.sel = selectors.DefaultSelector()
        self.handlers: dict[Address, Handler] = {}
        self.conns: dict[Address, _Conn] = {}  # peer/session -> connection
        # identity set of live connections: `conns` holds one entry PER
        # SESSION under multiplexing, so per-turn sweeps (flush) iterate
        # this instead of O(sessions) dict values
        self._links: dict[_Conn, None] = {}
        # connections with complete frames still buffered after their
        # dispatch budget ran out — drained first next pump turn
        self._hot: dict[_Conn, None] = {}
        # ingress gateway seam: notified of session aliasing and closes
        # (None when no gateway is installed — the pre-ingress behavior)
        self.ingress = None
        # Reconnect-with-backoff state, per dialed replica: a failed or
        # refused dial must not hot-loop SYNs at a dead peer (every send
        # would otherwise pay a socket+connect), and the window doubles
        # while the peer stays dead. replica -> [retry_at_monotonic,
        # current_delay_s]; absent = dial freely. `_was_connected` marks
        # replicas we reached at least once, so a successful re-dial
        # counts into bus.reconnects (first dials don't).
        self._dial_backoff: dict[int, list] = {}
        self._was_connected: set[int] = set()
        self.listener: socket.socket | None = None
        if listen:
            host, port = addresses[own_address]
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind((host, port))
            s.listen(listen_backlog)
            s.setblocking(False)
            self.listener = s
            self.sel.register(s, selectors.EVENT_READ, ("accept", None))

    # -- Network seam --

    def attach(self, addr: Address, handler: Handler) -> None:
        self.handlers[addr] = handler

    # Sends below this wbuf level defer their socket write to the pump
    # turn's flush: a window of replies coalesces into ONE send syscall
    # (and one TCP segment burst) instead of one per 128-byte reply — and
    # the clients' next requests then arrive together, which is what feeds
    # the replica's group-commit fusion.
    FLUSH_EAGER = 1 << 17

    def send(self, src: Address, dst: Address, data: bytes) -> str:
        """Queue `data` for `dst`. Returns the typed outcome: "sent",
        "shed_conn" (this peer's queue is capped), "shed_pool" (shared
        budget exhausted — backpressure, the protocol retransmits), or
        "unreachable". Existing callers may ignore the return value; the
        shed outcomes are also counted in the ingress.* metrics."""
        conn = self.conns.get(dst)
        if conn is None:
            if dst < len(self.addresses):
                conn = self._connect(dst)
            if conn is None:
                return "unreachable"  # VSR retransmits cover the loss
        if len(conn.wbuf) + len(data) > self.conn_send_max:
            self.pool.dropped += 1
            self._c_shed_conn.add()
            # Wedged-consumer defense: a CLIENT connection pinned at its
            # cap is not reading. Strikes accumulate per refused send and
            # reset whenever a flush makes progress; past the limit the
            # connection is cut (its sessions re-register on reconnect).
            # Replica links are exempt: consensus owns their retries.
            if conn.peer is None or conn.peer >= len(self.addresses):
                conn.strikes += 1
                if conn.strikes > self.wedged_strikes_max:
                    self._c_disconnect_wedged.add()
                    self._close(conn)
            return "shed_conn"  # drop for THIS peer, not for everyone
        if not self.pool.try_charge(len(data)):
            self._c_shed_pool.add()
            return "shed_pool"  # pool exhausted: backpressure
        conn.wbuf += data
        lat = self.latency
        if (
            lat is not None
            and lat.pending_egress
            and data[self._CMD_OFF] == _CMD_REPLY
        ):
            # sampled reply: claim its parked latency record for THIS
            # conn (the key re-derives from the frame bytes — client +
            # context — so no side channel rides the send path)
            tok = lat.pending_egress.pop(
                (
                    int.from_bytes(
                        data[self._CLIENT_OFF : self._CLIENT_OFF + 16],
                        "little",
                    ),
                    int.from_bytes(
                        data[self._CONTEXT_OFF : self._CONTEXT_OFF + 16],
                        "little",
                    ),
                ),
                None,
            )
            if tok is not None:
                conn.pending_lat.append(tok)
        if self.tracer.enabled and data[self._CMD_OFF] == _CMD_REPLY:
            # the op's egress hop: tag the flush that carries this reply
            # (tracked on the CONNECTION, so the tag lands on the flush
            # that actually writes this conn — never a neighbor's)
            conn.pending_traces.append(trace_id(
                int.from_bytes(
                    data[self._CLIENT_OFF : self._CLIENT_OFF + 16], "little"
                ),
                int.from_bytes(
                    data[self._CONTEXT_OFF : self._CONTEXT_OFF + 16],
                    "little",
                ),
            ))
        if len(conn.wbuf) >= self.FLUSH_EAGER:
            # large payloads start on the wire now; the eager flush
            # carries THIS conn's reply trace ids itself — left pending
            # they would mislabel the next flush_pending span
            if conn.pending_traces:
                traces, conn.pending_traces = conn.pending_traces, []
                with self.tracer.span("bus.flush", conns=1,
                                      traces=traces):
                    self._flush(conn)
            else:
                self._flush(conn)
        return "sent"

    def flush_pending(self) -> None:
        """Flush every connection's buffered sends (one syscall per conn
        per turn). pump() calls this on entry (so bytes queued between
        pumps never wait out a blocking select) and on exit (so sends
        queued by this turn's handlers leave with it)."""
        pending = [c for c in self._links if c.wbuf]
        if not pending:
            return
        self._c_flushes.add()
        traces: list[int] = []
        for conn in pending:
            if conn.pending_traces:
                traces.extend(conn.pending_traces)
                conn.pending_traces = []
        with self.tracer.span("bus.flush", conns=len(pending),
                              traces=traces):
            for conn in pending:
                self._flush(conn)

    # -- connections --

    DIAL_BACKOFF_MIN = 0.05  # first retry window after a failed dial
    DIAL_BACKOFF_MAX = 2.0  # ceiling while the peer stays dead

    def _dial_fail(self, replica: int) -> None:
        """A dial was refused/errored: arm (or double) the backoff window
        so sends stop paying a socket+SYN per attempt at a dead peer."""
        self._c_dial_failures.add()
        b = self._dial_backoff.get(replica)
        delay = self.DIAL_BACKOFF_MIN if b is None else min(
            self.DIAL_BACKOFF_MAX, b[1] * 2
        )
        self._dial_backoff[replica] = [_time.monotonic() + delay, delay]

    def _dial_ok(self, replica: int) -> None:
        self._dial_backoff.pop(replica, None)
        if replica in self._was_connected:
            self._c_reconnects.add()
        else:
            self._was_connected.add(replica)

    def _connect(self, replica: int) -> _Conn | None:
        # NON-BLOCKING dial: a blocked peer must never stall the event loop
        # (consensus for the live quorum would freeze for the TCP timeout).
        b = self._dial_backoff.get(replica)
        if b is not None and _time.monotonic() < b[0]:
            return None  # inside the backoff window: don't burn a dial
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setblocking(False)
        try:
            rc = s.connect_ex(self.addresses[replica])
        except OSError:
            s.close()
            self._dial_fail(replica)
            return None
        if rc not in (0, errno.EINPROGRESS, errno.EWOULDBLOCK):
            s.close()
            self._dial_fail(replica)
            return None
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn = _Conn(s, peer=replica, connected=(rc == 0))
        if rc == 0:
            self._dial_ok(replica)
        self.conns[replica] = conn
        self._links[conn] = None
        self.sel.register(
            s, selectors.EVENT_READ | selectors.EVENT_WRITE, ("conn", conn)
        )
        # identify ourselves so the acceptor can route replies (clients in
        # the u128 `client` field; replicas in the u8 `replica` field)
        hello = Header()
        if self.own < len(self.addresses):
            hello.replica = self.own
        else:
            hello.client = self.own
        hello.set_checksum_body(b"")
        hello.set_checksum()
        frame = hello.to_bytes()
        self.pool.used += len(frame)  # mandatory frame: charge unconditionally
        conn.wbuf += frame
        self._flush(conn)
        return conn

    def _accept(self) -> None:
        """Drain the accept queue: up to accept_budget pending connections
        per readiness event (a connect storm of hundreds used to land ONE
        accept per select round and stall for seconds)."""
        assert self.listener is not None
        for _ in range(self.accept_budget):
            try:
                s, _addr = self.listener.accept()
            except OSError:
                return
            s.setblocking(False)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _Conn(s)
            self._links[conn] = None
            self.sel.register(s, selectors.EVENT_READ, ("conn", conn))
            self._c_accepts.add()

    def _close(self, conn: _Conn) -> None:
        try:
            self.sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        conn.sock.close()
        self.pool.credit(len(conn.wbuf))  # unsent bytes return to the pool
        conn.wbuf.clear()
        if conn.pending_lat:
            # replies that never reached the wire: drop their records
            # (an egress stamp here would fabricate a latency)
            if self.latency is not None:
                for tok in conn.pending_lat:
                    self.latency.discard(tok)
            conn.pending_lat.clear()
        self._hot.pop(conn, None)
        self._links.pop(conn, None)
        # the gateway sees the close FIRST, while conn.sessions still
        # names the sessions routed here (it drops their table entries)
        if self.ingress is not None:
            self.ingress.on_conn_close(conn)
        # drop every routing entry aliased here (sessions + hello peer):
        # a reconnect re-learns them from its first frames
        for cid in conn.sessions:
            if self.conns.get(cid) is conn:
                del self.conns[cid]
        conn.sessions.clear()
        if conn.peer is not None and self.conns.get(conn.peer) is conn:
            del self.conns[conn.peer]

    def _flush(self, conn: _Conn) -> None:
        if not conn.connected:
            return  # dial still in progress; flushed on writability
        self._flush_io(conn)
        if conn.pending_lat:
            # reply_egress closes at the flush that first attempts the
            # socket write (a partial write still counts: the reply's
            # bytes started onto the wire with this syscall)
            lat = self.latency
            if lat is not None:
                for tok in conn.pending_lat:
                    lat.finish(tok)
            conn.pending_lat.clear()

    def _flush_io(self, conn: _Conn) -> None:
        while conn.wbuf:
            try:
                n = conn.sock.send(conn.wbuf)
            except OSError as e:
                if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                    return
                self._close(conn)
                return
            if n <= 0:
                return
            del conn.wbuf[:n]
            conn.strikes = 0  # the peer is reading again
            self.pool.credit(n)
            self._c_tx_bytes.add(n)

    # -- pumping --

    def pump(self, timeout: float = 0.01) -> int:
        """One event-loop turn: accept/read/dispatch. Returns frames
        dispatched. Hot connections (frames buffered past their budget
        last turn) are drained FIRST, before the select — fairness is
        round-robin across turns, not starvation of the patient."""
        dispatched = 0
        t0 = _time.perf_counter_ns() if self.metrics.enabled else 0
        self.flush_pending()  # deferred sends must not wait out the select
        if self._hot:
            timeout = 0.0  # buffered work exists: never block the select
            hot, self._hot = self._hot, {}
            for conn in hot:
                dispatched += self._drain(conn)
        for key, mask in self.sel.select(timeout):
            kind, conn = key.data
            if kind == "accept":
                self._accept()
                continue
            if mask & selectors.EVENT_WRITE and not conn.connected:
                # pending dial resolved: success or failure
                err = conn.sock.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
                if err != 0:
                    if conn.peer is not None:
                        self._dial_fail(conn.peer)
                    self._close(conn)
                    continue
                if conn.peer is not None:
                    self._dial_ok(conn.peer)
                conn.connected = True
                self.sel.modify(
                    conn.sock, selectors.EVENT_READ, ("conn", conn)
                )
                self._flush(conn)
            if not (mask & selectors.EVENT_READ):
                continue
            # Drain the socket buffer in one turn (a 1 MiB batch frame
            # spans many TCP segments; one recv per select round would cap
            # ingest at 64 KiB per event-loop turn). Bounded so one
            # firehose peer can't starve the rest of the loop. On FIN or
            # error, buffered frames STILL dispatch before the close —
            # a one-shot client may send its request and close.
            closing = False
            for _ in range(64):
                try:
                    chunk = conn.sock.recv(1 << 18)
                except OSError as e:
                    if e.errno not in (errno.EAGAIN, errno.EWOULDBLOCK):
                        closing = True
                    break
                if not chunk:
                    closing = True
                    break
                conn.rbuf += chunk
                if len(chunk) < (1 << 18):
                    break
            dispatched += self._drain(conn)
            if closing:
                self._close(conn)
        self.flush_pending()  # this turn's handler sends leave with it
        if dispatched and t0:
            # only turns that dispatched frames: idle selects would bury
            # the signal (and cost a histogram write per quiet turn)
            self._c_frames.add(dispatched)
            self.metrics.histogram("bus.pump_us").observe(
                (_time.perf_counter_ns() - t0) / 1000.0
            )
        return dispatched

    # Peeked header fields (framing + session aliasing read a handful of
    # bytes instead of parsing the full header — that parse, and the
    # checksum, belong to the handler): five u128s (80) + four u32s (16) +
    # three u64s (24) = size u32 at 120; client u128 at 48 (after
    # checksum, checksum_body, parent); request u32 at 80; command u8 at
    # 125. All cross-checked against Header at import.
    _SIZE_OFF = 120
    _CLIENT_OFF = 48
    _CONTEXT_OFF = 64  # context u128 (request checksum on reply frames)
    _REQUEST_OFF = 80
    _CMD_OFF = 125
    _OP_OFF = 126  # `operation` u8

    def _drain(self, conn: _Conn, budget: int | None = None) -> int:
        n = 0
        budget = self.dispatch_budget if budget is None else budget
        buf = conn.rbuf
        # frame-parse span: only when there is at least one parseable
        # frame AND tracing is on (pump calls _drain for every readable
        # conn; empty passes must stay free)
        tok = (
            self.tracer.start("bus.frame_parse")
            if self.tracer.enabled and len(buf) - conn.roff >= HEADER_SIZE
            else 0
        )
        # cluster-causal ingress anchor: the trace ids of the request
        # frames this parse pass dispatches (annotated onto the span at
        # the end — the ids are learned frame by frame)
        parse_traces: list[int] = [] if tok else None
        mv = memoryview(buf)
        try:
            while len(buf) - conn.roff >= HEADER_SIZE:
                if n >= budget:
                    # fairness: this peer used its turn; remaining frames
                    # stay buffered and the conn drains first next turn
                    self._hot[conn] = None
                    break
                o = conn.roff + self._SIZE_OFF
                size = int.from_bytes(mv[o : o + 4], "little")
                if size < HEADER_SIZE or size > self.message_size_max:
                    mv.release()
                    self._close(conn)  # corrupt framing: drop the conn
                    return n
                if len(buf) - conn.roff < size:
                    break
                frame = bytes(mv[conn.roff : conn.roff + size])
                conn.roff += size
                if conn.peer is None:
                    # first frame identifies the peer (hello or any
                    # message: the client field for clients, replica for
                    # replicas)
                    header = Header.from_bytes(frame[:HEADER_SIZE])
                    if not header.valid_checksum():
                        mv.release()
                        self._close(conn)
                        return n
                    peer = header.client if header.client else header.replica
                    conn.peer = peer
                    # Simultaneous dials create two links; keep the FIRST
                    # as canonical for sends (an overwrite would orphan
                    # its buffered partial frames) — this one stays
                    # readable.
                    if peer not in self.conns:
                        self.conns[peer] = conn
                    if header.client:
                        # the hello peer IS a session (the degenerate
                        # single-session case): track it like any alias
                        # so close/gateway bookkeeping is uniform
                        conn.sessions.add(peer)
                        if self.ingress is not None:
                            self.ingress.on_session(peer, conn)
                    if size == HEADER_SIZE and header.command == 0:
                        continue  # pure hello: consume
                # Session multiplexing: alias every request frame's client
                # id to this connection so the reply routes back here.
                # Latest-wins (a reconnecting session's new connection
                # takes over); the degenerate case — one session whose id
                # IS the hello peer — is a no-op dict hit.
                if frame[self._CMD_OFF] in (_CMD_REQUEST, _CMD_PING_CLIENT):
                    cid = int.from_bytes(
                        frame[self._CLIENT_OFF : self._CLIENT_OFF + 16],
                        "little",
                    )
                    # ping_client aliases too: an idle multiplexed session
                    # whose connection died re-attaches with its first
                    # ping — the pong must route over the NEW conn even
                    # before the session's next request re-aliases it
                    if cid and self.conns.get(cid) is not conn:
                        self._alias(cid, conn)
                    if frame[self._CMD_OFF] != _CMD_REQUEST:
                        cid = 0  # pings don't anchor trace ids
                    if parse_traces is not None and cid:
                        # ingress: the trace id is ASSIGNED here, from
                        # the request's own (client, checksum) pair
                        parse_traces.append(trace_id(
                            cid,
                            int.from_bytes(frame[0:16], "little"),
                        ))
                if self.demux:
                    # session-multiplexed client bus: route by the
                    # frame's client id (replies/busy/eviction all carry
                    # it), falling back to the bus's own handler
                    cid = int.from_bytes(
                        frame[self._CLIENT_OFF : self._CLIENT_OFF + 16],
                        "little",
                    )
                    handler = (
                        self.handlers.get(cid) or self.handlers.get(self.own)
                    )
                else:
                    handler = self.handlers.get(self.own)
                if handler is not None:
                    handler(conn.peer, frame)
                    n += 1
        finally:
            mv.release()
            if tok:
                if parse_traces:
                    self.tracer.annotate(tok, traces=parse_traces)
                self.tracer.stop(tok)
        # compact ONCE per turn (a del per frame moved the whole tail —
        # O(bytes) per 1 MiB batch frame — on every message)
        if conn.roff:
            if conn.roff == len(buf):
                buf.clear()
            else:
                del buf[: conn.roff]
            conn.roff = 0
        return n

    def drop_connections(self) -> None:
        """Fault-injection helper (chaos harness / tests): abruptly close
        every live connection with SO_LINGER=0, so the peer observes a
        RESET, not a graceful FIN. Recovery is the production path under
        test: the next send re-dials (with backoff), sessions re-alias,
        and the client runtime's timeouts retransmit what was in flight."""
        import struct as _struct

        for conn in list(self._links):
            try:
                conn.sock.setsockopt(
                    socket.SOL_SOCKET, socket.SO_LINGER,
                    _struct.pack("ii", 1, 0),
                )
            except OSError:
                pass
            self._close(conn)

    def _alias(self, cid: Address, conn: _Conn) -> None:
        old = self.conns.get(cid)
        if old is not None and old is not conn:
            old.sessions.discard(cid)
        self.conns[cid] = conn
        conn.sessions.add(cid)
        if self.ingress is not None:
            self.ingress.on_session(cid, conn)


# the framing/aliasing fast path peeks fields without parsing the header —
# pin the offsets against the Header layout so they can never drift
_CMD_REQUEST = int(Command.request)
_CMD_REPLY = int(Command.reply)
_CMD_PING_CLIENT = int(Command.ping_client)
_pin = Header(
    size=0x0BADF00D, client=0x0CAFE, context=0x0C0FFEE, request=0x0D15EA5E,
    command=int(Command.request), operation=0x42,
).to_bytes()
assert int.from_bytes(
    _pin[TCPMessageBus._SIZE_OFF : TCPMessageBus._SIZE_OFF + 4], "little"
) == 0x0BADF00D
assert int.from_bytes(
    _pin[TCPMessageBus._CLIENT_OFF : TCPMessageBus._CLIENT_OFF + 16],
    "little",
) == 0x0CAFE
assert int.from_bytes(
    _pin[TCPMessageBus._CONTEXT_OFF : TCPMessageBus._CONTEXT_OFF + 16],
    "little",
) == 0x0C0FFEE
assert int.from_bytes(
    _pin[TCPMessageBus._REQUEST_OFF : TCPMessageBus._REQUEST_OFF + 4],
    "little",
) == 0x0D15EA5E
assert _pin[TCPMessageBus._CMD_OFF] == _CMD_REQUEST
assert _pin[TCPMessageBus._OP_OFF] == 0x42
del _pin
