"""The Grid: the on-disk block store under the LSM forest.

The reference's design (reference: src/vsr/grid.zig:30-33, 731, 539):
fixed-size blocks addressed by u64 (address 0 = null), allocated from the
FreeSet, every block checksummed, reads served from a block cache first.
Blocks live in the Storage seam's grid zone ABOVE the checkpoint snapshot
areas (the zone is partitioned: snapshots | blocks).

Block wire format: [checksum u128][size u32][reserved u32][payload...]
padded to block_size (the reference prefixes blocks with a full vsr.Header;
the checksum-over-payload core is the same contract).
"""

from __future__ import annotations

from tigerbeetle_tpu import native
from tigerbeetle_tpu.io.storage import Storage, Zone
from tigerbeetle_tpu.lsm.cache import SetAssociativeCache
from tigerbeetle_tpu.metrics import NULL_METRICS
from tigerbeetle_tpu.vsr.free_set import FreeSet

BLOCK_SIZE = 128 * 1024  # reference: src/config.zig:140
_HEADER = 24  # checksum u128 + size u32 + reserved u32
BLOCK_PAYLOAD_MAX = BLOCK_SIZE - _HEADER


class GridBlockCorrupt(RuntimeError):
    """A block failed its embedded checksum/size validation. Carries the
    address so the VSR layer can repair it from peers instead of crashing
    (reference: src/vsr/grid.zig:731 read_block remote fallback +
    src/vsr/grid_blocks_missing.zig)."""

    def __init__(self, address: int, why: str):
        super().__init__(f"grid block {address}: {why}")
        self.address = address


class Grid:
    # observability seam (re-pointed by SpillManager.instrument / bench)
    metrics = NULL_METRICS

    def __init__(self, storage: Storage, offset: int, block_count: int,
                 cache_blocks: int = 256):
        """`offset`: byte offset within the grid zone where the block area
        starts (above the checkpoint snapshot areas)."""
        assert block_count % 64 == 0
        self.storage = storage
        self.offset = offset
        self.block_count = block_count
        self.free_set = FreeSet(block_count)
        # 16-way CLOCK block cache (reference: src/vsr/grid.zig set-
        # associative cache over 128 KiB blocks, src/config.zig:112)
        cap = max(16, (cache_blocks + 15) // 16 * 16)
        self.cache = SetAssociativeCache(cap)
        self.cache_blocks = cache_blocks
        # Released blocks stage here until the next checkpoint: the LAST
        # durable checkpoint's manifest may still reference them, so they
        # must not be reusable until a free set excluding them is encoded
        # (reference: src/vsr/superblock_free_set.zig — releases apply at
        # checkpoint, never mid-interval).
        self._staged_free: list[int] = []
        # Block IDENTITY registry: address -> expected payload checksum of
        # the block THIS replica wrote there. A block can carry a valid
        # self-checksum and still be the WRONG block for its address (a
        # peer whose layout diverged serving repair, a misdirected write) —
        # the registry is the parent-hash the reference gets from its
        # block-tree references (src/vsr/grid.zig block_id includes the
        # checksum). Consulted by read/verify/install; persisted at
        # checkpoint as a grid block chain (encode_chk_registry).
        self.block_chk: dict[int, int] = {}
        self._chk_chain: list[int] = []  # current registry chain blocks

    def _pos(self, address: int) -> int:
        assert 1 <= address <= self.block_count, address
        return self.offset + (address - 1) * BLOCK_SIZE

    # -- allocation --

    def acquire(self) -> int:
        r = self.free_set.reserve(1)
        if r is None:
            raise RuntimeError("grid full: no free blocks")
        address = self.free_set.acquire(r)
        self.free_set.forfeit(r)
        assert address is not None
        return address

    def release(self, address: int) -> None:
        """Stage the block for release at the NEXT checkpoint (see
        _staged_free) — crash-restore to the previous checkpoint must still
        find its contents intact."""
        assert 1 <= address <= self.block_count, address
        self._staged_free.append(address)
        self.cache.remove(address)

    # -- IO --

    def write_block(self, address: int, payload: bytes) -> None:
        assert len(payload) <= BLOCK_PAYLOAD_MAX, len(payload)
        chk = native.checksum(payload)
        head = (
            chk.to_bytes(16, "little")
            + len(payload).to_bytes(4, "little")
            + b"\x00" * 4
        )
        self.storage.write(Zone.grid, self._pos(address), head + payload)
        self.block_chk[address] = chk
        self._cache_put(address, payload)

    def create_block(self, payload: bytes) -> int:
        address = self.acquire()
        self.write_block(address, payload)
        return address

    @staticmethod
    def validate_raw(raw: bytes) -> bytes | None:
        """Parse + checksum-verify block wire bytes; the payload, or None
        if corrupt. The ONE implementation of the block header contract
        (all read/verify/install paths and state-sync installs use it)."""
        if len(raw) < _HEADER:
            return None
        size = int.from_bytes(raw[16:20], "little")
        if size > BLOCK_PAYLOAD_MAX or len(raw) < _HEADER + size:
            return None
        payload = raw[_HEADER : _HEADER + size]
        if native.checksum(payload) != int.from_bytes(raw[0:16], "little"):
            return None
        return payload

    def read_block(self, address: int) -> bytes:
        cached = self.cache.get(address)
        if cached is not None:
            return cached
        raw = self.storage.read(Zone.grid, self._pos(address), BLOCK_SIZE)
        self.metrics.counter("grid.block_reads").add()
        payload = self.validate_raw(raw)
        if payload is None:
            self.metrics.counter("grid.corrupt_blocks").add()
            raise GridBlockCorrupt(address, "bad checksum or size")
        exp = self.block_chk.get(address)
        if exp is not None and exp != int.from_bytes(raw[0:16], "little"):
            # self-consistent bytes but the WRONG block for this address
            self.metrics.counter("grid.corrupt_blocks").add()
            raise GridBlockCorrupt(address, "identity mismatch")
        self._cache_put(address, payload)
        return payload

    def verify_block(self, address: int) -> bool:
        """Verify a block in place (scrubbing; no cache effects): header
        self-checksum AND identity vs the registry. True = intact."""
        raw = self.storage.read(Zone.grid, self._pos(address), BLOCK_SIZE)
        if self.validate_raw(raw) is None:
            return False
        exp = self.block_chk.get(address)
        return exp is None or exp == int.from_bytes(raw[0:16], "little")

    def read_block_raw(self, address: int) -> bytes | None:
        """The block's verified on-disk bytes (header + payload), or None
        if corrupt — the repair-serving read (peers must not spread
        corruption)."""
        raw = self.storage.read(Zone.grid, self._pos(address), BLOCK_SIZE)
        size = int.from_bytes(raw[16:20], "little")
        if self.validate_raw(raw) is None:
            return None
        return raw[: _HEADER + size]

    def install_block_raw(self, address: int, raw: bytes) -> bool:
        """Install repaired block bytes at `address` — verified for BOTH
        self-consistency and identity (a diverged peer can serve bytes
        with a valid checksum that are the wrong block for this address;
        installing them would be silent corruption no later read could
        catch without the registry). Clears the cache entry so the next
        read sees the healed bytes."""
        if self.validate_raw(raw) is None:
            return False
        chk = int.from_bytes(raw[0:16], "little")
        exp = self.block_chk.get(address)
        if exp is not None and exp != chk:
            return False  # wrong-content repair: keep asking
        size = int.from_bytes(raw[16:20], "little")
        self.storage.write(Zone.grid, self._pos(address), raw[: _HEADER + size])
        if exp is None:
            # A block healed at an unregistered address gains identity
            # coverage NOW (and persists into the next checkpoint's
            # registry) — otherwise it would stay self-checksum-only and
            # be excluded from every future encode_chk_registry. Tradeoff:
            # with no registry entry there is nothing to verify content
            # AGAINST, so this pins the first-arriving valid bytes; a
            # diverged peer answering first wins the slot either way
            # (the old behavior also installed them, just unregistered) —
            # cross-replica state checks remain the backstop there.
            self.block_chk[address] = chk
        self.cache.remove(address)
        return True

    def _cache_put(self, address: int, payload: bytes) -> None:
        self.cache.put(address, payload)

    # -- checkpoint trailer --

    def encode_free_set(self) -> bytes:
        """Checkpoint trailer: apply staged releases, THEN encode — the new
        checkpoint's free set marks replaced blocks free (nothing in its
        manifests references them), and only once it is durable can they be
        reused. The caller must not create blocks between this call and the
        superblock write that records it."""
        for address in self._staged_free:
            self.free_set.release(address)
            self.block_chk.pop(address, None)
        self._staged_free.clear()
        return self.free_set.encode()

    def restore_free_set(self, data: bytes) -> None:
        self.free_set = FreeSet.decode(data, self.block_count)
        self._staged_free.clear()

    # -- the identity-registry chain (persisted alongside the free set;
    # the registry can exceed the superblock copy, so only the chain HEAD
    # (address + checksum) rides the checkpoint meta — the same trailer
    # pattern as the spill id-chain) --

    _CHK_ENTRY = 24  # addr u64 + checksum u128

    def encode_chk_registry(self) -> dict:
        """Write the registry into a fresh block chain (the old chain is
        released — staged, applied by the encode_free_set that MUST follow
        this call) and return the verified head pointer for the meta."""
        for address in self._chk_chain:
            self.release(address)
        # exclude staged frees: they leave block_chk at the encode that
        # follows, and persisting them would make a restarted replica's
        # registry (and therefore its chain layout and every later block
        # allocation) diverge from a peer that never restarted
        staged = set(self._staged_free)
        entries = sorted(
            (a, c) for a, c in self.block_chk.items() if a not in staged
        )
        per_block = (BLOCK_PAYLOAD_MAX - self._CHK_ENTRY) // self._CHK_ENTRY
        next_addr, next_chk = 0, 0
        chain: list[int] = []
        if entries:
            # written LAST chunk first so each block points at its successor
            last = ((len(entries) - 1) // per_block) * per_block
            for start in range(last, -1, -per_block):
                chunk = entries[start : start + per_block]
                payload = (
                    next_addr.to_bytes(8, "little")
                    + next_chk.to_bytes(16, "little")
                    + b"".join(
                        a.to_bytes(8, "little") + c.to_bytes(16, "little")
                        for a, c in chunk
                    )
                )
                next_addr = self.create_block(payload)
                next_chk = self.block_chk[next_addr]
                chain.append(next_addr)
        self._chk_chain = chain
        return {"addr": next_addr, "chk": f"{next_chk:x}"}

    def restore_chk_registry(self, head: dict | None) -> None:
        """Rebuild the registry by walking the chain from the verified
        head. A missing head (legacy checkpoint) leaves the registry empty
        — identity checks then degrade to self-checksum only. A CORRUPT
        chain block degrades the same way (empty registry + warning)
        instead of raising: this runs during local startup restore, where
        no peer-repair path exists yet — one latent sector error in the
        chain must not make restart unrecoverable. The registry is an
        extra verification layer over the self-checksums, never the data
        itself, so losing it costs coverage, not correctness."""
        self.block_chk = {}
        self._chk_chain = []
        if not head or not head.get("addr"):
            return
        addr = int(head["addr"])
        exp = int(head["chk"], 16)
        while addr:
            raw = self.storage.read(Zone.grid, self._pos(addr), BLOCK_SIZE)
            payload = self.validate_raw(raw)
            if payload is None or int.from_bytes(raw[0:16], "little") != exp:
                import sys

                sys.stderr.write(
                    f"warning: grid identity-registry chain corrupt at "
                    f"block {addr}; restoring with an EMPTY registry — "
                    "identity checks degrade to self-checksum only; "
                    "blocks regain registry coverage as they are "
                    "rewritten\n"
                )
                self.block_chk = {}
                self._chk_chain = []
                return
            self._chk_chain.append(addr)
            self.block_chk[addr] = exp
            next_addr = int.from_bytes(payload[0:8], "little")
            next_chk = int.from_bytes(payload[8:24], "little")
            for i in range(24, len(payload), self._CHK_ENTRY):
                a = int.from_bytes(payload[i : i + 8], "little")
                c = int.from_bytes(payload[i + 8 : i + 24], "little")
                self.block_chk[a] = c
            addr, exp = next_addr, next_chk
