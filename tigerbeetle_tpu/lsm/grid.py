"""The Grid: the on-disk block store under the LSM forest.

The reference's design (reference: src/vsr/grid.zig:30-33, 731, 539):
fixed-size blocks addressed by u64 (address 0 = null), allocated from the
FreeSet, every block checksummed, reads served from a block cache first.
Blocks live in the Storage seam's grid zone ABOVE the checkpoint snapshot
areas (the zone is partitioned: snapshots | blocks).

Block wire format: [checksum u128][size u32][reserved u32][payload...]
padded to block_size (the reference prefixes blocks with a full vsr.Header;
the checksum-over-payload core is the same contract).
"""

from __future__ import annotations

from tigerbeetle_tpu import native
from tigerbeetle_tpu.io.storage import Storage, Zone
from tigerbeetle_tpu.lsm.cache import SetAssociativeCache
from tigerbeetle_tpu.vsr.free_set import FreeSet

BLOCK_SIZE = 128 * 1024  # reference: src/config.zig:140
_HEADER = 24  # checksum u128 + size u32 + reserved u32
BLOCK_PAYLOAD_MAX = BLOCK_SIZE - _HEADER


class GridBlockCorrupt(RuntimeError):
    """A block failed its embedded checksum/size validation. Carries the
    address so the VSR layer can repair it from peers instead of crashing
    (reference: src/vsr/grid.zig:731 read_block remote fallback +
    src/vsr/grid_blocks_missing.zig)."""

    def __init__(self, address: int, why: str):
        super().__init__(f"grid block {address}: {why}")
        self.address = address


class Grid:
    def __init__(self, storage: Storage, offset: int, block_count: int,
                 cache_blocks: int = 256):
        """`offset`: byte offset within the grid zone where the block area
        starts (above the checkpoint snapshot areas)."""
        assert block_count % 64 == 0
        self.storage = storage
        self.offset = offset
        self.block_count = block_count
        self.free_set = FreeSet(block_count)
        # 16-way CLOCK block cache (reference: src/vsr/grid.zig set-
        # associative cache over 128 KiB blocks, src/config.zig:112)
        cap = max(16, (cache_blocks + 15) // 16 * 16)
        self.cache = SetAssociativeCache(cap)
        self.cache_blocks = cache_blocks
        # Released blocks stage here until the next checkpoint: the LAST
        # durable checkpoint's manifest may still reference them, so they
        # must not be reusable until a free set excluding them is encoded
        # (reference: src/vsr/superblock_free_set.zig — releases apply at
        # checkpoint, never mid-interval).
        self._staged_free: list[int] = []

    def _pos(self, address: int) -> int:
        assert 1 <= address <= self.block_count, address
        return self.offset + (address - 1) * BLOCK_SIZE

    # -- allocation --

    def acquire(self) -> int:
        r = self.free_set.reserve(1)
        if r is None:
            raise RuntimeError("grid full: no free blocks")
        address = self.free_set.acquire(r)
        self.free_set.forfeit(r)
        assert address is not None
        return address

    def release(self, address: int) -> None:
        """Stage the block for release at the NEXT checkpoint (see
        _staged_free) — crash-restore to the previous checkpoint must still
        find its contents intact."""
        assert 1 <= address <= self.block_count, address
        self._staged_free.append(address)
        self.cache.remove(address)

    # -- IO --

    def write_block(self, address: int, payload: bytes) -> None:
        assert len(payload) <= BLOCK_PAYLOAD_MAX, len(payload)
        head = (
            native.checksum(payload).to_bytes(16, "little")
            + len(payload).to_bytes(4, "little")
            + b"\x00" * 4
        )
        self.storage.write(Zone.grid, self._pos(address), head + payload)
        self._cache_put(address, payload)

    def create_block(self, payload: bytes) -> int:
        address = self.acquire()
        self.write_block(address, payload)
        return address

    @staticmethod
    def validate_raw(raw: bytes) -> bytes | None:
        """Parse + checksum-verify block wire bytes; the payload, or None
        if corrupt. The ONE implementation of the block header contract
        (all read/verify/install paths and state-sync installs use it)."""
        if len(raw) < _HEADER:
            return None
        size = int.from_bytes(raw[16:20], "little")
        if size > BLOCK_PAYLOAD_MAX or len(raw) < _HEADER + size:
            return None
        payload = raw[_HEADER : _HEADER + size]
        if native.checksum(payload) != int.from_bytes(raw[0:16], "little"):
            return None
        return payload

    def read_block(self, address: int) -> bytes:
        cached = self.cache.get(address)
        if cached is not None:
            return cached
        raw = self.storage.read(Zone.grid, self._pos(address), BLOCK_SIZE)
        payload = self.validate_raw(raw)
        if payload is None:
            raise GridBlockCorrupt(address, "bad checksum or size")
        self._cache_put(address, payload)
        return payload

    def verify_block(self, address: int) -> bool:
        """Checksum-verify a block in place (scrubbing; no cache effects).
        True = intact."""
        raw = self.storage.read(Zone.grid, self._pos(address), BLOCK_SIZE)
        return self.validate_raw(raw) is not None

    def read_block_raw(self, address: int) -> bytes | None:
        """The block's verified on-disk bytes (header + payload), or None
        if corrupt — the repair-serving read (peers must not spread
        corruption)."""
        raw = self.storage.read(Zone.grid, self._pos(address), BLOCK_SIZE)
        size = int.from_bytes(raw[16:20], "little")
        if self.validate_raw(raw) is None:
            return None
        return raw[: _HEADER + size]

    def install_block_raw(self, address: int, raw: bytes) -> bool:
        """Install repaired block bytes (verified) at `address`; clears the
        cache entry so the next read sees the healed bytes."""
        if self.validate_raw(raw) is None:
            return False
        size = int.from_bytes(raw[16:20], "little")
        self.storage.write(Zone.grid, self._pos(address), raw[: _HEADER + size])
        self.cache.remove(address)
        return True

    def _cache_put(self, address: int, payload: bytes) -> None:
        self.cache.put(address, payload)

    # -- checkpoint trailer --

    def encode_free_set(self) -> bytes:
        """Checkpoint trailer: apply staged releases, THEN encode — the new
        checkpoint's free set marks replaced blocks free (nothing in its
        manifests references them), and only once it is durable can they be
        reused. The caller must not create blocks between this call and the
        superblock write that records it."""
        for address in self._staged_free:
            self.free_set.release(address)
        self._staged_free.clear()
        return self.free_set.encode()

    def restore_free_set(self, data: bytes) -> None:
        self.free_set = FreeSet.decode(data, self.block_count)
        self._staged_free.clear()
