"""Grooves and the Forest: the object stores over LSM trees.

The reference's Groove (reference: src/lsm/groove.zig:23-77, 602-1010):
ObjectTree keyed by timestamp + IdTree mapping id -> timestamp + one
secondary index tree per struct field (comptime-generated from the fields
not in `ignored`, reference: src/lsm/groove.zig:137-157), with
get/insert/upsert and the prefetch contract (async load, then synchronous
get during commit). The Forest fans open/flush/checkpoint out to every
groove (reference: src/lsm/forest.zig:253-407).

Index trees use composite keys (reference: src/lsm/composite_key.zig):
big-endian field value ++ big-endian timestamp, so one range scan yields a
field value's matching timestamps in commit order. Upsert diffs old vs new
rows and touches only the CHANGED index trees (reference:
src/lsm/groove.zig:925-966 — balance mutations remove + reinsert).

The per-groove field lists mirror the reference's tree ids 1-24
(reference: src/state_machine.zig:67-100): accounts index
debits/credits_pending/posted, user_data_128/64/32, ledger, code (flags
and reserved ignored); transfers index debit/credit_account_id, amount,
pending_id, user_data_128/64/32, timeout, ledger, code (flags ignored).

Role in the TPU design: the HBM hash tables ARE the working set; this LSM
forest is the bounded-memory BACKING store once state exceeds HBM — cold
rows spill here (models/spill.py) and reload before a commit needs them.
Queries merge a device filter-scan over the HBM tables with index range
scans over the spilled tail (models/ledger.py query_*).
"""

from __future__ import annotations

from tigerbeetle_tpu.lsm.grid import Grid
from tigerbeetle_tpu.lsm.tree import Tree

ID_SIZE = 16
TS_SIZE = 8
OBJECT_SIZE = 128
TS_MAX = (1 << 64) - 1

# (name, byte offset in the 128-byte wire row, width) — little-endian fields
# (reference struct layouts: src/tigerbeetle.zig:7-40 Account, :64-89
# Transfer; index field sets: src/state_machine.zig:103-206).
ACCOUNT_INDEX_FIELDS = (
    ("debits_pending", 16, 16),
    ("debits_posted", 32, 16),
    ("credits_pending", 48, 16),
    ("credits_posted", 64, 16),
    ("user_data_128", 80, 16),
    ("user_data_64", 96, 8),
    ("user_data_32", 104, 4),
    ("ledger", 112, 4),
    ("code", 116, 2),
)
TRANSFER_INDEX_FIELDS = (
    ("debit_account_id", 16, 16),
    ("credit_account_id", 32, 16),
    ("amount", 48, 16),
    ("pending_id", 64, 16),
    ("user_data_128", 80, 16),
    ("user_data_64", 96, 8),
    ("user_data_32", 104, 4),
    ("timeout", 108, 4),
    ("ledger", 112, 4),
    ("code", 116, 2),
)


class Groove:
    def __init__(self, grid: Grid, memtable_max: int = 2048,
                 index_fields: tuple = (), manifest_log=None,
                 tree_ids: dict | None = None):
        tid = tree_ids or {}
        # ObjectTree: timestamp (big-endian, order-preserving) -> 128B row
        self.objects = Tree(grid, TS_SIZE, OBJECT_SIZE, memtable_max,
                            manifest_log=manifest_log,
                            tree_id=tid.get("timestamp", 0))
        # IdTree: id (big-endian u128) -> timestamp (reference IdTreeValue)
        self.ids = Tree(grid, ID_SIZE, TS_SIZE, memtable_max,
                        manifest_log=manifest_log, tree_id=tid.get("id", 0))
        # Secondary index trees: (field_be ++ ts_be) -> presence byte.
        # filters=False: index trees are range-scanned only (query()), and
        # bloom filters serve point lookups — building them was ~30% of a
        # spill cycle's LSM bill for nothing.
        self.index_spec = {name: (off, w) for name, off, w in index_fields}
        self.indexes = {
            name: Tree(grid, w + TS_SIZE, 1, memtable_max,
                       manifest_log=manifest_log, tree_id=tid.get(name, 0),
                       filters=False)
            for name, off, w in index_fields
        }
        # prefetch cache: id -> row (the CacheMap residency contract:
        # prefetched values stay resident through the commit, reference:
        # src/lsm/cache_map.zig:10-25)
        self.prefetched: dict[int, bytes | None] = {}

    @staticmethod
    def _id_key(id_: int) -> bytes:
        return id_.to_bytes(ID_SIZE, "big")

    @staticmethod
    def _ts_key(timestamp: int) -> bytes:
        return timestamp.to_bytes(TS_SIZE, "big")

    def _index_key(self, off: int, w: int, row: bytes, ts_key: bytes) -> bytes:
        return row[off : off + w][::-1] + ts_key  # LE field -> BE prefix

    # -- writes (reference: groove.insert/upsert/remove :902-966) --

    def insert(self, id_: int, timestamp: int, row: bytes) -> None:
        assert len(row) == OBJECT_SIZE
        ts_key = self._ts_key(timestamp)
        self.objects.put(ts_key, row)
        self.ids.put(self._id_key(id_), ts_key)
        for name, (off, w) in self.index_spec.items():
            self.indexes[name].put(
                self._index_key(off, w, row, ts_key), b"\x00"
            )

    def insert_bulk(self, rows_u8, timestamps, settle: bool = True) -> None:
        """Array-native bulk insert of n wire rows (np.uint8 [n, 128]) with
        their timestamps (np.uint64 [n]) — the spill cycle's write path.
        Key construction is numpy byte-slicing (big-endian composite keys
        built column-wise); each tree takes ONE put_array — no per-entry
        Python objects from here through the on-disk table write.
        settle=False defers all on-disk settling (the call cannot raise);
        the caller later settles each tree at a fault-retry-safe point."""
        import numpy as np

        n = len(rows_u8)
        if n == 0:
            return
        rows_u8 = np.ascontiguousarray(rows_u8)
        ts_be = np.ascontiguousarray(
            timestamps.astype(">u8")
        ).view(np.uint8).reshape(n, TS_SIZE)
        self.objects.put_array(ts_be, rows_u8, settle=settle)
        # id key: the 16 LE bytes at offset 0, reversed -> BE u128
        id_be = np.ascontiguousarray(rows_u8[:, ID_SIZE - 1 :: -1])
        self.ids.put_array(id_be, ts_be, settle=settle)
        for name, (off, w) in self.index_spec.items():
            field_be = rows_u8[:, off + w - 1 : (off - 1 if off else None) : -1]
            comp = np.concatenate(
                [np.ascontiguousarray(field_be), ts_be], axis=1
            )
            self.indexes[name].put_array(comp, b"\x00", settle=settle)

    def upsert(self, id_: int, timestamp: int, row: bytes,
               old_row: bytes | None = None) -> None:
        """Replace the object at `timestamp`. With `old_row`, only CHANGED
        index entries are removed/reinserted (reference diffs via the object
        cache, src/lsm/groove.zig:925-966); without it, the caller asserts
        the indexed fields are unchanged (e.g. re-spilling an identical
        immutable row)."""
        ts_key = self._ts_key(timestamp)
        self.objects.put(ts_key, row)
        self.ids.put(self._id_key(id_), ts_key)
        for name, (off, w) in self.index_spec.items():
            new_field = row[off : off + w]
            if old_row is None:
                self.indexes[name].put(
                    self._index_key(off, w, row, ts_key), b"\x00"
                )
            elif old_row[off : off + w] != new_field:
                self.indexes[name].remove(
                    self._index_key(off, w, old_row, ts_key)
                )
                self.indexes[name].put(
                    self._index_key(off, w, row, ts_key), b"\x00"
                )

    def remove(self, id_: int, timestamp: int,
               row: bytes | None = None) -> None:
        ts_key = self._ts_key(timestamp)
        self.objects.remove(ts_key)
        self.ids.remove(self._id_key(id_))
        if row is not None:
            for name, (off, w) in self.index_spec.items():
                self.indexes[name].remove(
                    self._index_key(off, w, row, ts_key)
                )

    # -- reads: prefetch then synchronous get (reference :608-760, 602) --

    def prefetch(self, ids: list[int]) -> None:
        """Load the working set (IdTree -> ObjectTree cascade). After this,
        get() is synchronous and pure — the property that lets the commit
        step run as one device kernel."""
        for id_ in ids:
            if id_ in self.prefetched:
                continue
            ts_key = self.ids.get(self._id_key(id_))
            self.prefetched[id_] = (
                None if ts_key is None else self.objects.get(ts_key)
            )

    def get(self, id_: int) -> bytes | None:
        assert id_ in self.prefetched, "get() before prefetch()"
        return self.prefetched[id_]

    def get_many_rows(
        self, ids: list[int]
    ) -> tuple[list[bytes | None], list[bytes | None]]:
        """Batched id -> (row, ts_key) via ONE multi-point-read per tree
        (IdTree then ObjectTree) instead of a full cascade per id — the
        spill reload's vectorized multi-lookup (reference prefetch contract,
        src/lsm/groove.zig:710-760). Positional: rows[i]/ts_keys[i] are
        None when ids[i] is absent."""
        ts_keys = self.ids.get_many([self._id_key(i) for i in ids])
        hit_idx = [i for i, t in enumerate(ts_keys) if t is not None]
        rows: list[bytes | None] = [None] * len(ids)
        if hit_idx:
            got = self.objects.get_many([ts_keys[i] for i in hit_idx])
            for i, row in zip(hit_idx, got):
                rows[i] = row
        return rows, ts_keys

    def prefetch_clear(self) -> None:
        self.prefetched.clear()

    # -- queries (reference: tree.zig:1126-1140 RangeQuery over an index) --

    def query(self, field: str, value: int, ts_min: int = 0,
              ts_max: int = TS_MAX) -> list[int]:
        """Timestamps of objects whose `field` equals `value`, ascending —
        one composite-key range scan."""
        off, w = self.index_spec[field]
        prefix = value.to_bytes(w, "big")
        lo = prefix + ts_min.to_bytes(TS_SIZE, "big")
        hi = prefix + ts_max.to_bytes(TS_SIZE, "big")
        return [
            int.from_bytes(k[-TS_SIZE:], "big")
            for k, _ in self.indexes[field].range(lo, hi)
        ]

    def get_by_timestamp(self, timestamp: int) -> bytes | None:
        return self.objects.get(self._ts_key(timestamp))

    # -- lifecycle --

    def flush(self) -> None:
        self.objects.flush()
        self.ids.flush()
        for tree in self.indexes.values():
            tree.flush()


# Tree id assignment mirrors the reference exactly (reference:
# src/state_machine.zig:67-100 tree_ids).
ACCOUNT_TREE_IDS = {
    "id": 1, "debits_pending": 2, "debits_posted": 3, "credits_pending": 4,
    "credits_posted": 5, "user_data_128": 6, "user_data_64": 7,
    "user_data_32": 8, "ledger": 9, "code": 10, "timestamp": 11,
}
TRANSFER_TREE_IDS = {
    "id": 12, "debit_account_id": 13, "credit_account_id": 14, "amount": 15,
    "pending_id": 16, "user_data_128": 17, "user_data_64": 18,
    "user_data_32": 19, "timeout": 20, "ledger": 21, "code": 22,
    "timestamp": 23,
}
POSTED_TREE_ID = 24


class Forest:
    """The grooves of the accounting state machine (reference:
    src/state_machine.zig:67-100: accounts, transfers, posted — tree ids
    1-24 incl. the per-field secondary indexes). Checkpoints persist the
    manifest INCREMENTALLY via the ManifestLog block chain
    (lsm/manifest_log.py; reference: src/lsm/manifest_log.zig)."""

    def __init__(self, grid: Grid, memtable_max: int = 2048):
        from tigerbeetle_tpu.lsm.manifest_log import ManifestLog

        self.grid = grid
        self.manifest_log = ManifestLog(grid)
        self.accounts = Groove(grid, memtable_max=memtable_max,
                               index_fields=ACCOUNT_INDEX_FIELDS,
                               manifest_log=self.manifest_log,
                               tree_ids=ACCOUNT_TREE_IDS)
        self.transfers = Groove(grid, memtable_max=memtable_max,
                                index_fields=TRANSFER_INDEX_FIELDS,
                                manifest_log=self.manifest_log,
                                tree_ids=TRANSFER_TREE_IDS)
        # posted: pending timestamp -> fulfillment byte (padded value)
        self.posted = Tree(grid, TS_SIZE, 1, memtable_max,
                           manifest_log=self.manifest_log,
                           tree_id=POSTED_TREE_ID)

    def _trees(self) -> list[Tree]:
        out = []
        for g in (self.accounts, self.transfers):
            out += [g.objects, g.ids, *g.indexes.values()]
        out.append(self.posted)
        return out

    def flush(self) -> None:
        self.accounts.flush()
        self.transfers.flush()
        self.posted.flush()

    def checkpoint(self) -> dict:
        """Flush everything, persist manifest churn to the log chain, and
        return the durable meta (manifest log blocks + identity registry
        head + free set — the superblock trailer contract, reference:
        src/vsr/superblock_manifest.zig). Block creation (manifest chain,
        then the registry chain capturing every live block's expected
        checksum) happens BEFORE the free set encode, which applies staged
        releases last."""
        self.flush()
        live = [t for tree in self._trees() for t in tree.live_tables()]
        mlog = self.manifest_log.checkpoint(live)
        block_chk = self.grid.encode_chk_registry()
        return {
            "manifest_log": mlog,
            "block_chk": block_chk,
            "free_set": self.grid.encode_free_set().hex(),
        }

    def restore(self, m: dict) -> None:
        # the registry FIRST: every later chain/table read then carries
        # identity verification, not just self-checksums
        self.grid.restore_chk_registry(m.get("block_chk"))
        levels = self.manifest_log.restore(m["manifest_log"])
        for tree in self._trees():
            assert tree.tree_id > 0
            tree.restore_levels(levels.get(tree.tree_id, {}))
        self.grid.restore_free_set(bytes.fromhex(m["free_set"]))
