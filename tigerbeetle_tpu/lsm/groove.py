"""Grooves and the Forest: the object stores over LSM trees.

The reference's Groove (reference: src/lsm/groove.zig:23-77, 602-1010):
ObjectTree keyed by timestamp + IdTree mapping id -> timestamp, with
get/insert/upsert and the prefetch contract (async load, then synchronous
get during commit). The Forest fans open/flush/checkpoint out to every
groove (reference: src/lsm/forest.zig:253-407).

Role in the TPU design: the HBM hash tables ARE the working set; this LSM
forest is the bounded-memory BACKING store once state exceeds HBM — cold
rows spill here (timestamp-keyed, id-indexed) and prefetch() pulls an id's
row back before a commit needs it. The spill/reload scheduler itself is
future work; the storage engine + contracts land here.
"""

from __future__ import annotations

from tigerbeetle_tpu.lsm.grid import Grid
from tigerbeetle_tpu.lsm.tree import Tree

ID_SIZE = 16
TS_SIZE = 8
OBJECT_SIZE = 128


class Groove:
    def __init__(self, grid: Grid, memtable_max: int = 2048):
        # ObjectTree: timestamp (big-endian, order-preserving) -> 128B row
        self.objects = Tree(grid, TS_SIZE, OBJECT_SIZE, memtable_max)
        # IdTree: id (big-endian u128) -> timestamp (reference IdTreeValue)
        self.ids = Tree(grid, ID_SIZE, TS_SIZE, memtable_max)
        # prefetch cache: id -> row (the CacheMap residency contract:
        # prefetched values stay resident through the commit, reference:
        # src/lsm/cache_map.zig:10-25)
        self.prefetched: dict[int, bytes | None] = {}

    @staticmethod
    def _id_key(id_: int) -> bytes:
        return id_.to_bytes(ID_SIZE, "big")

    @staticmethod
    def _ts_key(timestamp: int) -> bytes:
        return timestamp.to_bytes(TS_SIZE, "big")

    # -- writes (reference: groove.insert/upsert/remove :902-966) --

    def insert(self, id_: int, timestamp: int, row: bytes) -> None:
        assert len(row) == OBJECT_SIZE
        self.objects.put(self._ts_key(timestamp), row)
        self.ids.put(self._id_key(id_), self._ts_key(timestamp))

    def upsert(self, id_: int, timestamp: int, row: bytes) -> None:
        self.objects.put(self._ts_key(timestamp), row)
        self.ids.put(self._id_key(id_), self._ts_key(timestamp))

    def remove(self, id_: int, timestamp: int) -> None:
        self.objects.remove(self._ts_key(timestamp))
        self.ids.remove(self._id_key(id_))

    # -- reads: prefetch then synchronous get (reference :608-760, 602) --

    def prefetch(self, ids: list[int]) -> None:
        """Load the working set (IdTree -> ObjectTree cascade). After this,
        get() is synchronous and pure — the property that lets the commit
        step run as one device kernel."""
        for id_ in ids:
            if id_ in self.prefetched:
                continue
            ts_key = self.ids.get(self._id_key(id_))
            self.prefetched[id_] = (
                None if ts_key is None else self.objects.get(ts_key)
            )

    def get(self, id_: int) -> bytes | None:
        assert id_ in self.prefetched, "get() before prefetch()"
        return self.prefetched[id_]

    def prefetch_clear(self) -> None:
        self.prefetched.clear()

    # -- lifecycle --

    def flush(self) -> None:
        self.objects.flush()
        self.ids.flush()

    def manifest(self) -> dict:
        return {"objects": self.objects.manifest(), "ids": self.ids.manifest()}

    def restore_manifest(self, m: dict) -> None:
        self.objects.restore_manifest(m["objects"])
        self.ids.restore_manifest(m["ids"])


class Forest:
    """The grooves of the accounting state machine (reference:
    src/state_machine.zig:67-100: accounts, transfers, posted)."""

    def __init__(self, grid: Grid):
        self.grid = grid
        self.accounts = Groove(grid)
        self.transfers = Groove(grid)
        # posted: pending timestamp -> fulfillment byte (padded value)
        self.posted = Tree(grid, TS_SIZE, 1, 2048)

    def flush(self) -> None:
        self.accounts.flush()
        self.transfers.flush()
        self.posted.flush()

    def checkpoint(self) -> dict:
        """Flush everything and return the durable manifest (persisted in
        the superblock checkpoint meta alongside the free set)."""
        self.flush()
        return {
            "accounts": self.accounts.manifest(),
            "transfers": self.transfers.manifest(),
            "posted": self.posted.manifest(),
            "free_set": self.grid.encode_free_set().hex(),
        }

    def restore(self, m: dict) -> None:
        self.accounts.restore_manifest(m["accounts"])
        self.transfers.restore_manifest(m["transfers"])
        self.posted.restore_manifest(m["posted"])
        self.grid.restore_free_set(bytes.fromhex(m["free_set"]))
