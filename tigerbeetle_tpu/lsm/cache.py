"""Set-associative cache with CLOCK Nth-chance eviction.

The TPU build's analog of the reference's SetAssociativeCache (reference:
src/lsm/set_associative_cache.zig:15-22 Layout — 16 ways per set,
cache-line-packed metadata, CLOCK Nth-chance): fixed capacity, O(ways)
lookup, no per-entry allocation. Used as the grid block cache (the
reference uses it for the grid cache and the object cache; here the object
cache is HBM residency itself).

A key hashes to ONE set of `ways` slots. On hit, the slot's clock count
resets to 0. On insert into a full set, the clock hand sweeps the set
incrementing each slot's count until one exceeds `clock_bits` chances —
that slot is evicted (recently-hit slots survive longer).
"""

from __future__ import annotations

WAYS = 16  # reference: src/lsm/set_associative_cache.zig Layout.ways
CLOCK_CHANCES = 2  # Nth-chance: evict after N sweeps without a hit


class SetAssociativeCache:
    def __init__(self, capacity: int, ways: int = WAYS):
        assert capacity >= ways and capacity % ways == 0
        self.ways = ways
        self.sets = capacity // ways
        n = capacity
        self.keys: list[int | None] = [None] * n
        self.values: list[object] = [None] * n
        self.counts = bytearray(n)  # clock counts
        self.hands = bytearray(self.sets)  # per-set clock hand (way index)
        self.hits = 0
        self.misses = 0

    def _set_base(self, key: int) -> int:
        # splitmix-style finalizer — keys are block addresses (sequential),
        # so they must be scrambled across sets
        x = (key * 0x9E3779B97F4A7C15) & ((1 << 64) - 1)
        x ^= x >> 31
        return (x % self.sets) * self.ways

    def get(self, key: int):
        base = self._set_base(key)
        for i in range(base, base + self.ways):
            if self.keys[i] == key:
                self.counts[i] = 0  # touched: reset chances
                self.hits += 1
                return self.values[i]
        self.misses += 1
        return None

    def put(self, key: int, value) -> None:
        base = self._set_base(key)
        free = None
        for i in range(base, base + self.ways):
            if self.keys[i] == key:
                self.values[i] = value
                self.counts[i] = 0
                return
            if free is None and self.keys[i] is None:
                free = i
        if free is not None:
            self.keys[free] = key
            self.values[free] = value
            self.counts[free] = 0
            return
        # CLOCK Nth-chance sweep from the set's hand
        set_idx = base // self.ways
        hand = self.hands[set_idx]
        while True:
            i = base + hand
            hand = (hand + 1) % self.ways
            if self.counts[i] >= CLOCK_CHANCES:
                self.keys[i] = key
                self.values[i] = value
                self.counts[i] = 0
                self.hands[set_idx] = hand
                return
            self.counts[i] += 1

    def remove(self, key: int) -> None:
        base = self._set_base(key)
        for i in range(base, base + self.ways):
            if self.keys[i] == key:
                self.keys[i] = None
                self.values[i] = None
                self.counts[i] = 0
                return

    def clear(self) -> None:
        n = len(self.keys)
        self.keys = [None] * n
        self.values = [None] * n
        self.counts = bytearray(n)
        self.hands = bytearray(self.sets)

    def __contains__(self, key: int) -> bool:
        base = self._set_base(key)
        return any(self.keys[i] == key for i in range(base, base + self.ways))
