"""ManifestLog: incremental manifest persistence as grid block chains.

The reference's ManifestLog (reference: src/lsm/manifest_log.zig, 904 LoC;
superblock trailer records the block addresses,
src/vsr/superblock_manifest.zig): instead of serializing every tree's full
table list at each checkpoint, trees append TableInfo churn events
(insert/remove at a level) as they flush and compact; a checkpoint writes
only the NEW events since the last checkpoint as appended blocks. When
accumulated churn exceeds a multiple of the live table count, the chain is
compacted: rewritten as a snapshot of the live set and the old blocks
released (staged until the following checkpoint, lsm/grid.py).

Event wire form (JSON within a checksummed grid block):
    {"t": tree_id, "l": level, "op": "i"|"r", "info": TableInfo.to_json()}
Tree ids follow the reference's assignment (1-24,
reference: src/state_machine.zig:67-100).
"""

from __future__ import annotations

import json

from tigerbeetle_tpu.lsm.grid import BLOCK_PAYLOAD_MAX, Grid
from tigerbeetle_tpu.lsm.tree import TableInfo

COMPACT_CHURN_FACTOR = 4  # compact when events > max(64, factor * live)


class ManifestLog:
    def __init__(self, grid: Grid):
        self.grid = grid
        self.buffer: list[dict] = []  # events since the last checkpoint
        self.blocks: list[int] = []  # chain block addresses, oldest first
        self.event_count = 0  # events across the persisted chain

    # -- appends (called by trees as they mutate their table sets) --

    def append(self, tree_id: int, level: int, op: str, info: TableInfo) -> None:
        assert op in ("i", "r")
        self.buffer.append(
            {"t": tree_id, "l": level, "op": op, "info": info.to_json()}
        )

    # -- checkpoint --

    def checkpoint(self, live_tables: list[tuple[int, int, TableInfo]]) -> dict:
        """Persist buffered events; compact the chain first when churn
        dwarfs the live set (`live_tables`: every (tree_id, level, info)
        currently live). Returns the meta dict for the superblock. Must run
        BEFORE the grid free set is encoded (this creates/releases blocks).
        """
        total = self.event_count + len(self.buffer)
        if total > max(64, COMPACT_CHURN_FACTOR * len(live_tables)):
            for address in self.blocks:
                self.grid.release(address)
            self.blocks = []
            self.event_count = 0
            self.buffer = [
                {"t": t, "l": lv, "op": "i", "info": info.to_json()}
                for t, lv, info in live_tables
            ]
        if self.buffer:
            for chunk in _pack_chunks(self.buffer):
                self.blocks.append(self.grid.create_block(chunk))
            self.event_count += len(self.buffer)
            self.buffer = []
        return {"blocks": list(self.blocks), "events": self.event_count}

    # -- restore --

    def restore(self, meta: dict) -> dict[int, dict[int, list[TableInfo]]]:
        """Replay the chain chronologically; returns
        tree_id -> level -> [TableInfo] with level 0 NEWEST-FIRST (flush
        order) and deeper levels sorted by key range."""
        self.blocks = list(meta["blocks"])
        self.event_count = int(meta["events"])
        self.buffer = []
        levels: dict[int, dict[int, list[TableInfo]]] = {}
        for address in self.blocks:
            for ev in json.loads(self.grid.read_block(address)):
                per_tree = levels.setdefault(ev["t"], {})
                lvl = per_tree.setdefault(ev["l"], [])
                if ev["op"] == "i":
                    lvl.append(TableInfo.from_json(ev["info"]))
                else:
                    addr = ev["info"]["index_address"]
                    for i, info in enumerate(lvl):
                        if info.index_address == addr:
                            del lvl[i]
                            break
                    else:
                        raise RuntimeError(
                            f"manifest log: remove of unknown table {addr}"
                        )
        for per_tree in levels.values():
            for lv, infos in per_tree.items():
                if lv == 0:
                    infos.reverse()  # chronological -> newest-first
                else:
                    infos.sort(key=lambda x: x.key_min)
        return levels


def _pack_chunks(events: list[dict]) -> list[bytes]:
    """JSON-encode events into block-sized payloads."""
    out: list[bytes] = []
    batch: list[dict] = []
    size = 2
    for ev in events:
        enc = len(json.dumps(ev)) + 1
        if batch and size + enc > BLOCK_PAYLOAD_MAX:
            out.append(json.dumps(batch).encode())
            batch, size = [], 2
        batch.append(ev)
        size += enc
    if batch:
        out.append(json.dumps(batch).encode())
    return out
