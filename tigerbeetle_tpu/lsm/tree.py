"""One LSM tree over the Grid (reference: src/lsm/tree.zig, table.zig,
table_memory.zig, compaction.zig, manifest.zig — collapsed to their
load-bearing contracts):

- fixed-width keys (big-endian-comparable bytes) and values;
- a mutable in-memory table absorbs puts/removes; on flush it becomes an
  immutable ON-DISK table: sorted (key, value) pairs packed into grid data
  blocks plus one index block of first-keys (binary-searched on lookup);
- levels 0..n with growth factor 8: lookups cascade memtable -> level 0
  newest-first -> deeper levels; the first hit wins;
- compaction merges a level's tables into the next when the level exceeds
  its budget (k-way merge, newest-wins dedup, tombstone GC at the bottom);
- the manifest (table metadata: level, key range, block addresses) is a
  plain structure serialized with the tree's checkpoint (reference keeps a
  ManifestLog of blocks; here it rides the checkpoint trailer).

Tombstone = value of all 0xFF (valid object values never are: wire rows
carry nonzero ids in the id field's position).
"""

from __future__ import annotations

import dataclasses

from tigerbeetle_tpu.lsm.grid import BLOCK_PAYLOAD_MAX, Grid

GROWTH_FACTOR = 8  # reference: src/config.zig:142
LEVEL0_TABLES_MAX = 4


@dataclasses.dataclass
class TableInfo:
    """Manifest entry (reference: src/lsm/manifest.zig TableInfo)."""

    index_address: int
    key_min: bytes
    key_max: bytes
    entry_count: int

    def to_json(self):
        return {
            "index_address": self.index_address,
            "key_min": self.key_min.hex(),
            "key_max": self.key_max.hex(),
            "entry_count": self.entry_count,
        }

    @staticmethod
    def from_json(d):
        return TableInfo(
            index_address=d["index_address"],
            key_min=bytes.fromhex(d["key_min"]),
            key_max=bytes.fromhex(d["key_max"]),
            entry_count=d["entry_count"],
        )


class Tree:
    def __init__(self, grid: Grid, key_size: int, value_size: int,
                 memtable_max: int = 4096):
        self.grid = grid
        self.key_size = key_size
        self.value_size = value_size
        self.entry_size = key_size + value_size
        self.entries_per_block = BLOCK_PAYLOAD_MAX // self.entry_size
        self.memtable_max = memtable_max
        self.memtable: dict[bytes, bytes] = {}
        self.tombstone = b"\xff" * value_size
        # levels[0] is newest-first; deeper levels hold older data
        self.levels: list[list[TableInfo]] = [[]]

    # -- writes --

    def put(self, key: bytes, value: bytes) -> None:
        assert len(key) == self.key_size and len(value) == self.value_size
        assert value != self.tombstone
        self.memtable[key] = value
        if len(self.memtable) >= self.memtable_max:
            self.flush()

    def remove(self, key: bytes) -> None:
        assert len(key) == self.key_size
        self.memtable[key] = self.tombstone

    # -- reads (the lookup cascade, reference: src/lsm/tree.zig:303-433) --

    def get(self, key: bytes) -> bytes | None:
        hit = self.memtable.get(key)
        if hit is not None:
            return None if hit == self.tombstone else hit
        for level in self.levels:
            for info in level:  # newest-first within a level
                if info.key_min <= key <= info.key_max:
                    hit = self._table_get(info, key)
                    if hit is not None:
                        return None if hit == self.tombstone else hit
        return None

    def _table_get(self, info: TableInfo, key: bytes) -> bytes | None:
        index = self.grid.read_block(info.index_address)
        # index payload: [addr u64][first_key key_size] per data block
        rec = 8 + self.key_size
        n = len(index) // rec
        lo, hi = 0, n - 1
        pos = 0
        while lo <= hi:  # last block whose first key <= key
            mid = (lo + hi) // 2
            first = index[mid * rec + 8 : mid * rec + 8 + self.key_size]
            if first <= key:
                pos = mid
                lo = mid + 1
            else:
                hi = mid - 1
        addr = int.from_bytes(index[pos * rec : pos * rec + 8], "little")
        data = self.grid.read_block(addr)
        e = self.entry_size
        lo, hi = 0, len(data) // e - 1
        while lo <= hi:
            mid = (lo + hi) // 2
            k = data[mid * e : mid * e + self.key_size]
            if k == key:
                return data[mid * e + self.key_size : (mid + 1) * e]
            if k < key:
                lo = mid + 1
            else:
                hi = mid - 1
        return None

    # -- flush / compaction --

    def flush(self) -> None:
        if not self.memtable:
            return
        items = sorted(self.memtable.items())
        self.memtable = {}
        self.levels[0].insert(0, self._write_table(items))
        self._maybe_compact()

    def _write_table(self, items: list[tuple[bytes, bytes]]) -> TableInfo:
        index = bytearray()
        for i in range(0, len(items), self.entries_per_block):
            chunk = items[i : i + self.entries_per_block]
            payload = b"".join(k + v for k, v in chunk)
            addr = self.grid.create_block(payload)
            index += addr.to_bytes(8, "little") + chunk[0][0]
        index_address = self.grid.create_block(bytes(index))
        return TableInfo(
            index_address=index_address,
            key_min=items[0][0], key_max=items[-1][0],
            entry_count=len(items),
        )

    def _level_budget(self, level: int) -> int:
        return LEVEL0_TABLES_MAX * (GROWTH_FACTOR ** level)

    def _maybe_compact(self) -> None:
        for level in range(len(self.levels)):
            if len(self.levels[level]) > self._level_budget(level):
                self._compact_level(level)

    def _compact_level(self, level: int) -> None:
        """Merge ALL of `level` into `level+1` (the reference paces one
        table per half-bar; whole-level merges trade pacing for simplicity
        while preserving the shape: newer level wins, bottom level drops
        tombstones — reference: src/lsm/compaction.zig:1-32)."""
        if level + 1 >= len(self.levels):
            self.levels.append([])
        merged: dict[bytes, bytes] = {}
        # strictly oldest-first so newer entries overwrite: the DEEPER
        # level's tables (older data) first, each level oldest-to-newest
        # (lists are newest-first)
        for info in (
            list(reversed(self.levels[level + 1]))
            + list(reversed(self.levels[level]))
        ):
            merged.update(self._read_table(info))
            self.grid_release_table(info)
        bottom = level + 1 == len(self.levels) - 1
        items = sorted(
            (k, v)
            for k, v in merged.items()
            if not (bottom and v == self.tombstone)  # tombstone GC
        )
        self.levels[level] = []
        self.levels[level + 1] = [self._write_table(items)] if items else []

    def _read_table(self, info: TableInfo) -> dict[bytes, bytes]:
        out: dict[bytes, bytes] = {}
        index = self.grid.read_block(info.index_address)
        rec = 8 + self.key_size
        e = self.entry_size
        for i in range(len(index) // rec):
            addr = int.from_bytes(index[i * rec : i * rec + 8], "little")
            data = self.grid.read_block(addr)
            for j in range(len(data) // e):
                out[data[j * e : j * e + self.key_size]] = \
                    data[j * e + self.key_size : (j + 1) * e]
        return out

    def grid_release_table(self, info: TableInfo) -> None:
        index = self.grid.read_block(info.index_address)
        rec = 8 + self.key_size
        for i in range(len(index) // rec):
            self.grid.release(int.from_bytes(index[i * rec : i * rec + 8], "little"))
        self.grid.release(info.index_address)

    # -- checkpoint --

    def manifest(self) -> list:
        """The durable table metadata (flush() first for completeness)."""
        return [
            [info.to_json() for info in level] for level in self.levels
        ]

    def restore_manifest(self, manifest: list) -> None:
        self.levels = [
            [TableInfo.from_json(d) for d in level] for level in manifest
        ]
        self.memtable = {}
