"""One LSM tree over the Grid (reference: src/lsm/tree.zig, table.zig,
table_memory.zig, compaction.zig, manifest.zig — collapsed to their
load-bearing contracts):

- fixed-width keys (big-endian-comparable bytes) and values;
- a mutable in-memory table absorbs puts/removes; on flush it becomes an
  immutable ON-DISK table: sorted (key, value) pairs packed into grid data
  blocks plus one index block of first-keys (binary-searched on lookup);
- level 0 holds overlapping tables newest-first (flush targets); levels
  >= 1 hold DISJOINT tables sorted by key range (reference invariant,
  src/lsm/manifest_level.zig), found by binary search on lookup;
- compaction is PACED: one table per compact step — the over-budget
  level's victim table merges with the intersecting tables of the next
  level (k-way, newest-wins dedup), output split into bounded tables,
  tombstone GC at the bottom (reference: src/lsm/compaction.zig:1-32 one
  table per half-bar). A flush triggers at most one paced step per level
  (the half-bar analog), with a 2x-budget backpressure loop as the
  hard bound;
- the manifest (table metadata: level, key range, block addresses) is a
  plain structure serialized with the tree's checkpoint (reference keeps a
  ManifestLog of blocks; lsm/manifest_log.py provides the incremental
  block-chain form used by the forest checkpoint).

Tombstone = value of all 0xFF (valid object values never are: wire rows
carry nonzero ids in the id field's position).
"""

from __future__ import annotations

import dataclasses
import hashlib

from tigerbeetle_tpu.lsm.grid import BLOCK_PAYLOAD_MAX, Grid

GROWTH_FACTOR = 8  # reference: src/config.zig:142
LEVEL0_TABLES_MAX = 4

# Split-block-style bloom filter (reference: src/lsm/bloom_filter.zig):
# ~10 bits/key, 4 probes -> ~1-2% false positives. The filter is its own
# grid block per table, consulted before any index/data block read.
FILTER_BITS_PER_KEY = 10
FILTER_PROBES = 4


# Filter format v1: "BF02"-prefixed bits built with the VECTORIZED
# polynomial hash below (building 10M+ keys through per-key blake2b
# dominated whole spill cycles). The authoritative version marker is
# TableInfo.filter_version (persisted in the manifest) — payload sniffing
# alone could misread a legacy blake2b filter whose first bytes collide
# with the magic (~2^-32/filter, but a false NEGATIVE would silently skip
# a table). Legacy version-0 filters keep the blake2b probes.
FILTER_MAGIC = b"BF02"
_POLY = 0x100000001B3  # FNV-ish odd multiplier (mod 2^64)
_MIX1 = 0xFF51AFD7ED558CCD
_MIX2 = 0xC4CEB9FE1A85EC53
_M64 = (1 << 64) - 1


def _poly_hash_scalar(key: bytes) -> tuple[int, int]:
    h = 0xCBF29CE484222325
    for b in key:
        h = ((h ^ b) * _POLY) & _M64
    h ^= h >> 33
    h1 = (h * _MIX1) & _M64
    h1 ^= h1 >> 29
    h2 = ((h * _MIX2) & _M64) | 1
    return h1, h2


def _filter_probes(key: bytes, nbits: int):
    """Legacy (unversioned) probe positions — blake2b."""
    d = hashlib.blake2b(key, digest_size=16).digest()
    h1 = int.from_bytes(d[:8], "little")
    h2 = int.from_bytes(d[8:], "little") | 1
    return ((h1 + i * h2) % nbits for i in range(FILTER_PROBES))


def build_filter(keys, count: int) -> bytes:
    """Split-block-style filter over fixed-size keys, built VECTORIZED:
    one polynomial pass over the key byte columns + one scattered
    bitwise-or per probe (numpy), instead of a Python blake2b per key."""
    import numpy as np

    # multiple of 8 so the query side's len*8 equals the build-side modulus
    nbits = (max(64, count * FILTER_BITS_PER_KEY) + 7) // 8 * 8
    bits = np.zeros(nbits // 8, dtype=np.uint8)
    keys = list(keys)
    if keys:
        n = len(keys)
        ksz = len(keys[0])
        arr = np.frombuffer(b"".join(keys), dtype=np.uint8).reshape(n, ksz)
        h = np.full(n, 0xCBF29CE484222325, dtype=np.uint64)
        poly = np.uint64(_POLY)
        for j in range(ksz):
            h = (h ^ arr[:, j].astype(np.uint64)) * poly
        h ^= h >> np.uint64(33)
        h1 = h * np.uint64(_MIX1)
        h1 ^= h1 >> np.uint64(29)
        h2 = (h * np.uint64(_MIX2)) | np.uint64(1)
        for i in range(FILTER_PROBES):
            p = (h1 + np.uint64(i) * h2) % np.uint64(nbits)
            np.bitwise_or.at(
                bits, (p >> np.uint64(3)).astype(np.int64),
                (np.uint8(1) << (p & np.uint64(7)).astype(np.uint8)),
            )
    return FILTER_MAGIC + bits.tobytes()


def filter_may_contain(filt: bytes, key: bytes, version: int = 1) -> bool:
    if version >= 1 and filt.startswith(FILTER_MAGIC):
        bits = filt[len(FILTER_MAGIC):]
        nbits = len(bits) * 8
        if nbits == 0:
            return True
        h1, h2 = _poly_hash_scalar(key)
        # (h1 + i*h2) wraps mod 2^64 BEFORE the modulus (the vectorized
        # builder computes in u64; nbits does not divide 2^64)
        return all(
            bits[p >> 3] & (1 << (p & 7))
            for p in (
                ((h1 + i * h2) & _M64) % nbits for i in range(FILTER_PROBES)
            )
        )
    nbits = len(filt) * 8  # legacy blake2b filter
    if nbits == 0:
        return True
    return all(
        filt[p >> 3] & (1 << (p & 7)) for p in _filter_probes(key, nbits)
    )


@dataclasses.dataclass
class TableInfo:
    """Manifest entry (reference: src/lsm/manifest.zig TableInfo)."""

    index_address: int
    key_min: bytes
    key_max: bytes
    entry_count: int
    filter_address: int = 0  # 0 = no filter (pre-filter manifests)
    filter_version: int = 0  # 0 = legacy blake2b probes, 1 = BF02 poly

    def to_json(self):
        return {
            "index_address": self.index_address,
            "key_min": self.key_min.hex(),
            "key_max": self.key_max.hex(),
            "entry_count": self.entry_count,
            "filter_address": self.filter_address,
            "filter_version": self.filter_version,
        }

    @staticmethod
    def from_json(d):
        return TableInfo(
            index_address=d["index_address"],
            key_min=bytes.fromhex(d["key_min"]),
            key_max=bytes.fromhex(d["key_max"]),
            entry_count=d["entry_count"],
            filter_address=d.get("filter_address", 0),
            filter_version=d.get("filter_version", 0),
        )


def _bisect_table(level: list[TableInfo], key: bytes) -> int | None:
    """Index of the (disjoint, sorted) table whose range covers key."""
    lo, hi = 0, len(level) - 1
    while lo <= hi:
        mid = (lo + hi) // 2
        t = level[mid]
        if key < t.key_min:
            hi = mid - 1
        elif key > t.key_max:
            lo = mid + 1
        else:
            return mid
    return None


class Tree:
    def __init__(self, grid: Grid, key_size: int, value_size: int,
                 memtable_max: int = 4096, manifest_log=None,
                 tree_id: int = 0):
        self.grid = grid
        self.manifest_log = manifest_log  # emits TableInfo churn events
        self.tree_id = tree_id
        self.key_size = key_size
        self.value_size = value_size
        self.entry_size = key_size + value_size
        self.entries_per_block = BLOCK_PAYLOAD_MAX // self.entry_size
        self.memtable_max = memtable_max
        self.table_entries_max = memtable_max * 4  # merge output table size
        self.memtable: dict[bytes, bytes] = {}
        self.tombstone = b"\xff" * value_size
        # levels[0]: overlapping, newest-first. levels[i>=1]: disjoint,
        # sorted by key range (reference: src/lsm/manifest_level.zig).
        self.levels: list[list[TableInfo]] = [[]]
        self._compact_cursor: dict[int, int] = {}  # level -> round-robin pos

    # -- writes --

    def put(self, key: bytes, value: bytes) -> None:
        assert len(key) == self.key_size and len(value) == self.value_size
        assert value != self.tombstone
        self.memtable[key] = value
        if len(self.memtable) >= self.memtable_max:
            self.flush()

    def put_many(self, keys, values) -> None:
        """Bulk put: one C-speed dict update per chunk instead of a Python
        call per key (the spill cycle feeds 12 trees x 100k+ rows; per-key
        put() was the dominant cost of a cycle). `values` is a parallel
        list or ONE shared value (secondary-index presence bytes)."""
        if not keys:
            return
        if isinstance(values, (bytes, bytearray)):
            assert len(values) == self.value_size
            pairs = ((k, values) for k in keys)
        else:
            pairs = zip(keys, values)
        # chunked so the memtable flushes near its budget (a single giant
        # update would build one oversized on-disk table)
        it = iter(pairs)
        while True:
            room = max(self.memtable_max - len(self.memtable), 1024)
            chunk = []
            for _ in range(room):
                try:
                    chunk.append(next(it))
                except StopIteration:
                    break
            if not chunk:
                break
            self.memtable.update(chunk)
            if len(self.memtable) >= self.memtable_max:
                self.flush()

    def remove(self, key: bytes) -> None:
        assert len(key) == self.key_size
        self.memtable[key] = self.tombstone

    # -- reads (the lookup cascade, reference: src/lsm/tree.zig:303-433) --

    def get(self, key: bytes) -> bytes | None:
        hit = self.memtable.get(key)
        if hit is not None:
            return None if hit == self.tombstone else hit
        for info in self.levels[0]:  # newest-first, overlapping
            if info.key_min <= key <= info.key_max:
                hit = self._table_get(info, key)
                if hit is not None:
                    return None if hit == self.tombstone else hit
        for level in self.levels[1:]:  # disjoint: binary search by range
            i = _bisect_table(level, key)
            if i is not None:
                hit = self._table_get(level[i], key)
                if hit is not None:
                    return None if hit == self.tombstone else hit
        return None

    def range(self, lo: bytes, hi: bytes) -> list[tuple[bytes, bytes]]:
        """All live (key, value) pairs with lo <= key <= hi, ascending.
        Newest-wins across memtable/levels; tombstones excluded (reference:
        src/lsm/tree.zig:1126-1140 RangeQuery over levels)."""
        assert len(lo) == self.key_size and len(hi) == self.key_size
        out: dict[bytes, bytes] = {}
        # oldest-first so newer entries overwrite: deepest level first, each
        # level oldest-to-newest (lists are newest-first)
        for level in reversed(self.levels):
            for info in reversed(level):
                if info.key_max < lo or info.key_min > hi:
                    continue
                out.update(self._table_range(info, lo, hi))
        for k, v in self.memtable.items():
            if lo <= k <= hi:
                out[k] = v
        return sorted(
            (k, v) for k, v in out.items() if v != self.tombstone
        )

    def _table_range(self, info: TableInfo, lo: bytes,
                     hi: bytes) -> dict[bytes, bytes]:
        """One table's entries in [lo, hi]: binary-search the index block for
        the first candidate data block, then walk blocks until past hi."""
        index = self.grid.read_block(info.index_address)
        rec = 8 + self.key_size
        n = len(index) // rec
        # last block whose first key <= lo (earlier blocks cannot contain lo)
        pos = 0
        a, b = 0, n - 1
        while a <= b:
            mid = (a + b) // 2
            first = index[mid * rec + 8 : mid * rec + 8 + self.key_size]
            if first <= lo:
                pos = mid
                a = mid + 1
            else:
                b = mid - 1
        out: dict[bytes, bytes] = {}
        e = self.entry_size
        for i in range(pos, n):
            first = index[i * rec + 8 : i * rec + 8 + self.key_size]
            if first > hi:
                break
            addr = int.from_bytes(index[i * rec : i * rec + 8], "little")
            data = self.grid.read_block(addr)
            for j in range(len(data) // e):
                k = data[j * e : j * e + self.key_size]
                if k < lo:
                    continue
                if k > hi:
                    break
                out[k] = data[j * e + self.key_size : (j + 1) * e]
        return out

    def _table_get(self, info: TableInfo, key: bytes) -> bytes | None:
        if info.filter_address:
            # bloom check first: a negative skips the index+data reads
            # entirely (reference: src/lsm/bloom_filter.zig consulted in
            # lookup_from_levels_storage)
            if not filter_may_contain(
                self.grid.read_block(info.filter_address), key,
                version=info.filter_version,
            ):
                return None
        index = self.grid.read_block(info.index_address)
        # index payload: [addr u64][first_key key_size] per data block
        rec = 8 + self.key_size
        n = len(index) // rec
        lo, hi = 0, n - 1
        pos = 0
        while lo <= hi:  # last block whose first key <= key
            mid = (lo + hi) // 2
            first = index[mid * rec + 8 : mid * rec + 8 + self.key_size]
            if first <= key:
                pos = mid
                lo = mid + 1
            else:
                hi = mid - 1
        addr = int.from_bytes(index[pos * rec : pos * rec + 8], "little")
        data = self.grid.read_block(addr)
        e = self.entry_size
        lo, hi = 0, len(data) // e - 1
        while lo <= hi:
            mid = (lo + hi) // 2
            k = data[mid * e : mid * e + self.key_size]
            if k == key:
                return data[mid * e + self.key_size : (mid + 1) * e]
            if k < key:
                lo = mid + 1
            else:
                hi = mid - 1
        return None

    # -- flush / compaction --

    def flush(self) -> None:
        if not self.memtable:
            return
        items = sorted(self.memtable.items())
        self.memtable = {}
        info = self._write_table(items)
        self.levels[0].insert(0, info)
        self._log("i", 0, info)
        self._maybe_compact()

    def _log(self, op: str, level: int, info: TableInfo) -> None:
        if self.manifest_log is not None:
            self.manifest_log.append(self.tree_id, level, op, info)

    def _write_table(self, items: list[tuple[bytes, bytes]]) -> TableInfo:
        index = bytearray()
        for i in range(0, len(items), self.entries_per_block):
            chunk = items[i : i + self.entries_per_block]
            payload = b"".join(k + v for k, v in chunk)
            addr = self.grid.create_block(payload)
            index += addr.to_bytes(8, "little") + chunk[0][0]
        index_address = self.grid.create_block(bytes(index))
        filter_address = self.grid.create_block(
            build_filter((k for k, _ in items), len(items))
        )
        return TableInfo(
            index_address=index_address,
            key_min=items[0][0], key_max=items[-1][0],
            entry_count=len(items),
            filter_address=filter_address,
            filter_version=1,
        )

    def _level_budget(self, level: int) -> int:
        return LEVEL0_TABLES_MAX * (GROWTH_FACTOR ** level)

    def _maybe_compact(self) -> None:
        """At most ONE paced table merge per over-budget level per call
        (the half-bar analog); a 2x-budget backpressure loop bounds the
        worst case (reference paces compaction so a level can never run
        away, src/lsm/compaction.zig:1-32)."""
        for level in range(len(self.levels)):
            budget = self._level_budget(level)
            if len(self.levels[level]) > budget:
                self._compact_one(level)
            while len(self.levels[level]) > 2 * budget:
                self._compact_one(level)

    def _compact_one(self, level: int) -> None:
        """Merge ONE victim table from `level` with the intersecting tables
        of `level+1`: a STREAMING two-way merge, block-at-a-time, with
        bounded buffers — host memory stays O(block + output table), never
        O(level) (reference: src/lsm/compaction.zig:1-32 streams via
        iterators over grid blocks). Newest-wins dedup (the victim is one
        level above, hence strictly newer); tombstone GC at the bottom."""
        if level + 1 >= len(self.levels):
            self.levels.append([])
        src, dst = self.levels[level], self.levels[level + 1]
        if level == 0:
            victim = src.pop()  # oldest level-0 table
        else:
            cur = self._compact_cursor.get(level, 0) % len(src)
            victim = src.pop(cur)
            self._compact_cursor[level] = cur  # next table shifts into place
        # intersecting run in the (sorted, disjoint) destination level
        lo_i = 0
        while lo_i < len(dst) and dst[lo_i].key_max < victim.key_min:
            lo_i += 1
        hi_i = lo_i
        while hi_i < len(dst) and dst[hi_i].key_min <= victim.key_max:
            hi_i += 1
        olds = dst[lo_i:hi_i]
        bottom = (
            level + 1 == len(self.levels) - 1
            or all(not lvl for lvl in self.levels[level + 2 :])
        )

        def old_stream():  # disjoint + sorted: concatenation is sorted
            for info in olds:
                yield from self._iter_table(info)

        out = self._write_merged(
            self._iter_table(victim), old_stream(), drop_tombstones=bottom
        )
        for info in olds:
            self.grid_release_table(info)
            self._log("r", level + 1, info)
        self.grid_release_table(victim)
        self._log("r", level, victim)
        for info in out:
            self._log("i", level + 1, info)
        self.levels[level + 1] = dst[:lo_i] + out + dst[hi_i:]

    def _iter_table(self, info: TableInfo):
        """Stream a table's (key, value) pairs, one data block resident at
        a time."""
        index = self.grid.read_block(info.index_address)
        rec = 8 + self.key_size
        e = self.entry_size
        for i in range(len(index) // rec):
            addr = int.from_bytes(index[i * rec : i * rec + 8], "little")
            data = self.grid.read_block(addr)
            for j in range(len(data) // e):
                yield (
                    data[j * e : j * e + self.key_size],
                    data[j * e + self.key_size : (j + 1) * e],
                )

    _SENTINEL = (None, None)

    def _write_merged(self, new_iter, old_iter, drop_tombstones: bool):
        """Two-way streaming merge (new wins on equal keys) into bounded
        output tables. Peak host memory: one input block per stream (grid
        cache) + one output table's items."""
        out_tables: list[TableInfo] = []
        items: list[tuple[bytes, bytes]] = []

        def emit(k, v):
            if drop_tombstones and v == self.tombstone:
                return
            items.append((k, v))
            if len(items) >= self.table_entries_max:
                out_tables.append(self._write_table(items))
                items.clear()

        nk, nv = next(new_iter, self._SENTINEL)
        ok, ov = next(old_iter, self._SENTINEL)
        while nk is not None or ok is not None:
            if ok is None or (nk is not None and nk <= ok):
                if nk == ok:  # superseded old entry: drop it
                    ok, ov = next(old_iter, self._SENTINEL)
                emit(nk, nv)
                nk, nv = next(new_iter, self._SENTINEL)
            else:
                emit(ok, ov)
                ok, ov = next(old_iter, self._SENTINEL)
        if items:
            out_tables.append(self._write_table(items))
        return out_tables

    def grid_release_table(self, info: TableInfo) -> None:
        index = self.grid.read_block(info.index_address)
        rec = 8 + self.key_size
        for i in range(len(index) // rec):
            self.grid.release(int.from_bytes(index[i * rec : i * rec + 8], "little"))
        self.grid.release(info.index_address)
        if info.filter_address:
            self.grid.release(info.filter_address)

    # -- checkpoint (persisted via the ManifestLog, lsm/manifest_log.py) --

    def live_tables(self) -> list:
        """(tree_id, level, info) of every live table — the manifest log's
        compaction snapshot input. Level 0 is emitted OLDEST-FIRST: the
        log's restore replays events chronologically and rebuilds level 0
        newest-first by reversing, so snapshot events must read like the
        original insert order."""
        out = [(self.tree_id, 0, info) for info in reversed(self.levels[0])]
        for level, tables in enumerate(self.levels[1:], start=1):
            out += [(self.tree_id, level, info) for info in tables]
        return out

    def restore_levels(self, per_level: dict[int, list[TableInfo]]) -> None:
        """Adopt levels replayed from the manifest log."""
        n = max(per_level, default=0) + 1
        self.levels = [per_level.get(i, []) for i in range(max(n, 1))]
        self.memtable = {}
        self._compact_cursor = {}
