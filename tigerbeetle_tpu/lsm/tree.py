"""One LSM tree over the Grid (reference: src/lsm/tree.zig, table.zig,
table_memory.zig, compaction.zig, manifest.zig — collapsed to their
load-bearing contracts):

- fixed-width keys (big-endian-comparable bytes) and values;
- a mutable in-memory table absorbs puts/removes; on flush it becomes an
  immutable ON-DISK table: sorted (key, value) pairs packed into grid data
  blocks plus one index block of first-keys (binary-searched on lookup);
- level 0 holds overlapping tables newest-first (flush targets); levels
  >= 1 hold DISJOINT tables sorted by key range (reference invariant,
  src/lsm/manifest_level.zig), found by binary search on lookup;
- compaction is PACED: one table per compact step — the over-budget
  level's victim table merges with the intersecting tables of the next
  level (k-way, newest-wins dedup), output split into bounded tables,
  tombstone GC at the bottom (reference: src/lsm/compaction.zig:1-32 one
  table per half-bar). A flush triggers at most one paced step per level
  (the half-bar analog), with a 2x-budget backpressure loop as the
  hard bound;
- the manifest (table metadata: level, key range, block addresses) is a
  plain structure serialized with the tree's checkpoint (reference keeps a
  ManifestLog of blocks; lsm/manifest_log.py provides the incremental
  block-chain form used by the forest checkpoint).

Tombstone = value of all 0xFF (valid object values never are: wire rows
carry nonzero ids in the id field's position).
"""

from __future__ import annotations

import dataclasses
import hashlib

import numpy as np

from tigerbeetle_tpu.lsm.grid import BLOCK_PAYLOAD_MAX, Grid
from tigerbeetle_tpu.metrics import NULL_METRICS
from tigerbeetle_tpu.tracer import NULL_TRACER

GROWTH_FACTOR = 8  # reference: src/config.zig:142
LEVEL0_TABLES_MAX = 4

# Split-block-style bloom filter (reference: src/lsm/bloom_filter.zig):
# ~10 bits/key, 4 probes -> ~1-2% false positives. The filter is its own
# grid block per table, consulted before any index/data block read.
FILTER_BITS_PER_KEY = 10
FILTER_PROBES = 4


# Filter format v1: "BF02"-prefixed bits built with the VECTORIZED
# polynomial hash below (building 10M+ keys through per-key blake2b
# dominated whole spill cycles). The authoritative version marker is
# TableInfo.filter_version (persisted in the manifest) — payload sniffing
# alone could misread a legacy blake2b filter whose first bytes collide
# with the magic (~2^-32/filter, but a false NEGATIVE would silently skip
# a table). Legacy version-0 filters keep the blake2b probes.
FILTER_MAGIC = b"BF02"
_POLY = 0x100000001B3  # FNV-ish odd multiplier (mod 2^64)
_MIX1 = 0xFF51AFD7ED558CCD
_MIX2 = 0xC4CEB9FE1A85EC53
_M64 = (1 << 64) - 1


def _poly_hash_scalar(key: bytes) -> tuple[int, int]:
    h = 0xCBF29CE484222325
    for b in key:
        h = ((h ^ b) * _POLY) & _M64
    h ^= h >> 33
    h1 = (h * _MIX1) & _M64
    h1 ^= h1 >> 29
    h2 = ((h * _MIX2) & _M64) | 1
    return h1, h2


def _filter_probes(key: bytes, nbits: int):
    """Legacy (unversioned) probe positions — blake2b."""
    d = hashlib.blake2b(key, digest_size=16).digest()
    h1 = int.from_bytes(d[:8], "little")
    h2 = int.from_bytes(d[8:], "little") | 1
    return ((h1 + i * h2) % nbits for i in range(FILTER_PROBES))


def build_filter(keys, count: int) -> bytes:
    """Split-block-style filter over fixed-size keys, built VECTORIZED:
    one polynomial pass over the key byte columns + one scattered
    bitwise-or per probe (numpy), instead of a Python blake2b per key.
    `keys` is an iterable of key bytes OR a packed np.uint8 [n, key_size]
    array (the array-native table-write path)."""
    # multiple of 8 so the query side's len*8 equals the build-side modulus
    nbits = (max(64, count * FILTER_BITS_PER_KEY) + 7) // 8 * 8
    bits = np.zeros(nbits // 8, dtype=np.uint8)
    if isinstance(keys, np.ndarray):
        arr = keys
    else:
        keys = list(keys)
        arr = (
            np.frombuffer(b"".join(keys), dtype=np.uint8)
            .reshape(len(keys), len(keys[0]))
            if keys else None
        )
    if arr is not None and len(arr):
        n, ksz = arr.shape
        h = np.full(n, 0xCBF29CE484222325, dtype=np.uint64)
        poly = np.uint64(_POLY)
        for j in range(ksz):
            h = (h ^ arr[:, j].astype(np.uint64)) * poly
        h ^= h >> np.uint64(33)
        h1 = h * np.uint64(_MIX1)
        h1 ^= h1 >> np.uint64(29)
        h2 = (h * np.uint64(_MIX2)) | np.uint64(1)
        for i in range(FILTER_PROBES):
            p = (h1 + np.uint64(i) * h2) % np.uint64(nbits)
            np.bitwise_or.at(
                bits, (p >> np.uint64(3)).astype(np.int64),
                (np.uint8(1) << (p & np.uint64(7)).astype(np.uint8)),
            )
    return FILTER_MAGIC + bits.tobytes()


def filter_may_contain_many(filt: bytes, keys_u8: np.ndarray,
                            version: int = 1) -> np.ndarray:
    """Vectorized membership probe: one polynomial pass over the packed
    key matrix (np.uint8 [n, key_size]) + FILTER_PROBES scattered bit
    tests — the batch analog of filter_may_contain, amortizing the hash
    over the whole id set (the multi-lookup path). Legacy (version-0)
    filters fall back to the scalar blake2b probes per key."""
    n = len(keys_u8)
    if n == 0:
        return np.zeros(0, dtype=bool)
    if not (version >= 1 and filt.startswith(FILTER_MAGIC)):
        return np.array([
            filter_may_contain(filt, k.tobytes(), version=version)
            for k in keys_u8
        ])
    bits = np.frombuffer(filt, dtype=np.uint8, offset=len(FILTER_MAGIC))
    nbits = len(bits) * 8
    if nbits == 0:
        return np.ones(n, dtype=bool)
    h = np.full(n, 0xCBF29CE484222325, dtype=np.uint64)
    poly = np.uint64(_POLY)
    for j in range(keys_u8.shape[1]):
        h = (h ^ keys_u8[:, j].astype(np.uint64)) * poly
    h ^= h >> np.uint64(33)
    h1 = h * np.uint64(_MIX1)
    h1 ^= h1 >> np.uint64(29)
    h2 = (h * np.uint64(_MIX2)) | np.uint64(1)
    may = np.ones(n, dtype=bool)
    for i in range(FILTER_PROBES):
        p = (h1 + np.uint64(i) * h2) % np.uint64(nbits)
        may &= (
            bits[(p >> np.uint64(3)).astype(np.int64)]
            & (np.uint8(1) << (p & np.uint64(7)).astype(np.uint8))
        ) != 0
    return may


def filter_may_contain(filt: bytes, key: bytes, version: int = 1) -> bool:
    if version >= 1 and filt.startswith(FILTER_MAGIC):
        bits = filt[len(FILTER_MAGIC):]
        nbits = len(bits) * 8
        if nbits == 0:
            return True
        h1, h2 = _poly_hash_scalar(key)
        # (h1 + i*h2) wraps mod 2^64 BEFORE the modulus (the vectorized
        # builder computes in u64; nbits does not divide 2^64)
        return all(
            bits[p >> 3] & (1 << (p & 7))
            for p in (
                ((h1 + i * h2) & _M64) % nbits for i in range(FILTER_PROBES)
            )
        )
    nbits = len(filt) * 8  # legacy blake2b filter
    if nbits == 0:
        return True
    return all(
        filt[p >> 3] & (1 << (p & 7)) for p in _filter_probes(key, nbits)
    )


@dataclasses.dataclass
class TableInfo:
    """Manifest entry (reference: src/lsm/manifest.zig TableInfo)."""

    index_address: int
    key_min: bytes
    key_max: bytes
    entry_count: int
    filter_address: int = 0  # 0 = no filter (pre-filter manifests)
    filter_version: int = 0  # 0 = legacy blake2b probes, 1 = BF02 poly

    def to_json(self):
        return {
            "index_address": self.index_address,
            "key_min": self.key_min.hex(),
            "key_max": self.key_max.hex(),
            "entry_count": self.entry_count,
            "filter_address": self.filter_address,
            "filter_version": self.filter_version,
        }

    @staticmethod
    def from_json(d):
        return TableInfo(
            index_address=d["index_address"],
            key_min=bytes.fromhex(d["key_min"]),
            key_max=bytes.fromhex(d["key_max"]),
            entry_count=d["entry_count"],
            filter_address=d.get("filter_address", 0),
            filter_version=d.get("filter_version", 0),
        )


def _bisect_table(level: list[TableInfo], key: bytes) -> int | None:
    """Index of the (disjoint, sorted) table whose range covers key."""
    lo, hi = 0, len(level) - 1
    while lo <= hi:
        mid = (lo + hi) // 2
        t = level[mid]
        if key < t.key_min:
            hi = mid - 1
        elif key > t.key_max:
            lo = mid + 1
        else:
            return mid
    return None


class Tree:
    # observability seams (SpillManager.instrument / the bench re-point
    # these at the shared registry; defaults cost nothing)
    metrics = NULL_METRICS
    tracer = NULL_TRACER

    def __init__(self, grid: Grid, key_size: int, value_size: int,
                 memtable_max: int = 4096, manifest_log=None,
                 tree_id: int = 0, filters: bool = True):
        self.grid = grid
        self.manifest_log = manifest_log  # emits TableInfo churn events
        self.tree_id = tree_id
        # bloom filters serve _table_get point lookups only; trees that are
        # exclusively range-scanned (secondary indexes) skip the build
        self.filters = filters
        self.key_size = key_size
        self.value_size = value_size
        self.entry_size = key_size + value_size
        self.entries_per_block = BLOCK_PAYLOAD_MAX // self.entry_size
        self.memtable_max = memtable_max
        self.table_entries_max = memtable_max * 4  # merge output table size
        self.memtable: dict[bytes, bytes] = {}
        self.tombstone = b"\xff" * value_size
        # levels[0]: overlapping, newest-first. levels[i>=1]: disjoint,
        # sorted by key range (reference: src/lsm/manifest_level.zig).
        self.levels: list[list[TableInfo]] = [[]]
        self._compact_cursor: dict[int, int] = {}  # level -> round-robin pos
        # pending put_array buffers, settled into sorted L0 tables in bulk
        # (one big sort + fewer, larger tables = less write amplification
        # than per-chunk insertion). INVARIANT: at most one of (memtable,
        # _pending) is non-empty — every entry point settles/flushes the
        # other first, so newest-wins ordering across the two paths holds.
        self._pending: list[tuple[np.ndarray, np.ndarray | bytes]] = []
        self._pending_rows = 0
        self.settle_max = 16 * memtable_max
        # An interrupted compaction (GridBlockCorrupt mid-merge-read) must
        # RESUME at the next settle point, before any further block
        # allocation — otherwise a healed-and-retried replica compacts in
        # a different order than its peers and the grids' block layouts
        # diverge (repair-by-address depends on layout determinism).
        self._compact_debt = False

    # -- writes --

    def put(self, key: bytes, value: bytes) -> None:
        assert len(key) == self.key_size and len(value) == self.value_size
        assert value != self.tombstone
        if self._pending or self._compact_debt:
            self._settle()
        self.memtable[key] = value
        if len(self.memtable) >= self.memtable_max:
            self.flush()

    def put_many(self, keys, values) -> None:
        """Bulk put: one C-speed dict update per chunk instead of a Python
        call per key (the spill cycle feeds 12 trees x 100k+ rows; per-key
        put() was the dominant cost of a cycle). `values` is a parallel
        list or ONE shared value (secondary-index presence bytes)."""
        if not keys:
            return
        if self._pending or self._compact_debt:
            self._settle()
        if isinstance(values, (bytes, bytearray)):
            assert len(values) == self.value_size
            pairs = ((k, values) for k in keys)
        else:
            pairs = zip(keys, values)
        # chunked so the memtable flushes near its budget (a single giant
        # update would build one oversized on-disk table)
        it = iter(pairs)
        while True:
            room = max(self.memtable_max - len(self.memtable), 1024)
            chunk = []
            for _ in range(room):
                try:
                    chunk.append(next(it))
                except StopIteration:
                    break
            if not chunk:
                break
            self.memtable.update(chunk)
            if len(self.memtable) >= self.memtable_max:
                self.flush()

    def remove(self, key: bytes) -> None:
        assert len(key) == self.key_size
        if self._pending or self._compact_debt:
            self._settle()
        self.memtable[key] = self.tombstone

    # -- reads (the lookup cascade, reference: src/lsm/tree.zig:303-433) --

    def get(self, key: bytes) -> bytes | None:
        if self._pending or self._compact_debt:
            self._settle()
        hit = self.memtable.get(key)
        if hit is not None:
            return None if hit == self.tombstone else hit
        for info in self.levels[0]:  # newest-first, overlapping
            if info.key_min <= key <= info.key_max:
                hit = self._table_get(info, key)
                if hit is not None:
                    return None if hit == self.tombstone else hit
        for level in self.levels[1:]:  # disjoint: binary search by range
            i = _bisect_table(level, key)
            if i is not None:
                hit = self._table_get(level[i], key)
                if hit is not None:
                    return None if hit == self.tombstone else hit
        return None

    def get_many(self, keys: list[bytes]) -> list[bytes | None]:
        """Batched point reads: one memtable pass, then each LEVEL is
        walked once for the whole unresolved set — per-table bloom probes
        run vectorized over the candidate batch and each index block is
        parsed once per table per call, not once per key (the reference
        saturates IO depth across a prefetch batch the same way,
        src/lsm/groove.zig:710-760). Results are positional: out[i] is the
        live value for keys[i] or None (missing or tombstone). Equivalent
        to [self.get(k) for k in keys] by construction — the cascade
        resolves each key at the NEWEST occurrence, same as get()."""
        if self._pending or self._compact_debt:
            self._settle()
        with self.tracer.span("lsm.get_many", ids=len(keys)), \
                self.metrics.histogram("lsm.get_many_us").time():
            out = self._get_many(keys)
        self.metrics.counter("lsm.lookup_batches").add()
        self.metrics.counter("lsm.lookup_ids").add(len(keys))
        return out

    def _get_many(self, keys: list[bytes]) -> list[bytes | None]:
        n = len(keys)
        out: list[bytes | None] = [None] * n
        mt = self.memtable
        tomb = self.tombstone
        unresolved: set[int] = set()
        for i, k in enumerate(keys):
            hit = mt.get(k)
            if hit is None:
                unresolved.add(i)
            elif hit != tomb:
                out[i] = hit
        # level 0: overlapping tables newest-first — each table claims the
        # candidates in its key range that an older table must not shadow
        for info in self.levels[0]:
            if not unresolved:
                return out
            cand = [
                i for i in sorted(unresolved)
                if info.key_min <= keys[i] <= info.key_max
            ]
            if cand:
                self._table_get_many(info, keys, cand, out, unresolved)
        # levels >= 1: disjoint sorted tables — group the (sorted)
        # unresolved keys by covering table with one merge walk per level
        for level in self.levels[1:]:
            if not unresolved:
                return out
            if not level:
                continue
            order = sorted(unresolved, key=lambda i: keys[i])
            t = 0
            by_table: dict[int, list[int]] = {}
            for i in order:
                k = keys[i]
                while t < len(level) and level[t].key_max < k:
                    t += 1
                if t == len(level):
                    break
                if level[t].key_min <= k:
                    by_table.setdefault(t, []).append(i)
            for t, cand in by_table.items():
                self._table_get_many(level[t], keys, cand, out, unresolved)
        return out

    def _table_get_many(self, info: TableInfo, keys: list[bytes],
                        cand: list[int], out: list,
                        unresolved: set[int]) -> None:
        """Resolve `cand` (indices into keys) against ONE table: vectorized
        bloom probe over the batch, one index-block parse, then per-data-
        block grouped binary searches. Hits (including tombstones) are
        recorded in `out` and removed from `unresolved` — a hit at this
        depth shadows every older occurrence."""
        ksz = self.key_size
        if info.filter_address:
            keys_u8 = np.frombuffer(
                b"".join(keys[i] for i in cand), dtype=np.uint8
            ).reshape(len(cand), ksz)
            may = filter_may_contain_many(
                self.grid.read_block(info.filter_address), keys_u8,
                version=info.filter_version,
            )
            n_probed = len(cand)
            cand = [i for i, m in zip(cand, may) if m]
            self.metrics.counter("lsm.bloom_probes").add(n_probed)
            self.metrics.counter("lsm.bloom_negatives").add(
                n_probed - len(cand)
            )
            if not cand:
                return
        index = self.grid.read_block(info.index_address)
        rec = 8 + ksz
        nb = len(index) // rec
        firsts = [index[j * rec + 8 : j * rec + 8 + ksz] for j in range(nb)]
        from bisect import bisect_right

        by_block: dict[int, list[int]] = {}
        for i in cand:
            pos = max(0, bisect_right(firsts, keys[i]) - 1)
            by_block.setdefault(pos, []).append(i)
        e = self.entry_size
        tomb = self.tombstone
        for pos, members in by_block.items():
            addr = int.from_bytes(index[pos * rec : pos * rec + 8], "little")
            data = self.grid.read_block(addr)
            ne = len(data) // e
            for i in members:
                key = keys[i]
                lo, hi = 0, ne - 1
                while lo <= hi:
                    mid = (lo + hi) // 2
                    k = data[mid * e : mid * e + ksz]
                    if k == key:
                        v = data[mid * e + ksz : (mid + 1) * e]
                        if v != tomb:
                            out[i] = v
                        unresolved.discard(i)
                        break
                    if k < key:
                        lo = mid + 1
                    else:
                        hi = mid - 1

    def range(self, lo: bytes, hi: bytes) -> list[tuple[bytes, bytes]]:
        """All live (key, value) pairs with lo <= key <= hi, ascending.
        Newest-wins across memtable/levels; tombstones excluded (reference:
        src/lsm/tree.zig:1126-1140 RangeQuery over levels)."""
        assert len(lo) == self.key_size and len(hi) == self.key_size
        if self._pending or self._compact_debt:
            self._settle()
        out: dict[bytes, bytes] = {}
        # oldest-first so newer entries overwrite: deepest level first, each
        # level oldest-to-newest (lists are newest-first)
        for level in reversed(self.levels):
            for info in reversed(level):
                if info.key_max < lo or info.key_min > hi:
                    continue
                out.update(self._table_range(info, lo, hi))
        for k, v in self.memtable.items():
            if lo <= k <= hi:
                out[k] = v
        return sorted(
            (k, v) for k, v in out.items() if v != self.tombstone
        )

    def _table_range(self, info: TableInfo, lo: bytes,
                     hi: bytes) -> dict[bytes, bytes]:
        """One table's entries in [lo, hi]: binary-search the index block for
        the first candidate data block, then walk blocks until past hi."""
        index = self.grid.read_block(info.index_address)
        rec = 8 + self.key_size
        n = len(index) // rec
        # last block whose first key <= lo (earlier blocks cannot contain lo)
        pos = 0
        a, b = 0, n - 1
        while a <= b:
            mid = (a + b) // 2
            first = index[mid * rec + 8 : mid * rec + 8 + self.key_size]
            if first <= lo:
                pos = mid
                a = mid + 1
            else:
                b = mid - 1
        out: dict[bytes, bytes] = {}
        e = self.entry_size
        for i in range(pos, n):
            first = index[i * rec + 8 : i * rec + 8 + self.key_size]
            if first > hi:
                break
            addr = int.from_bytes(index[i * rec : i * rec + 8], "little")
            data = self.grid.read_block(addr)
            for j in range(len(data) // e):
                k = data[j * e : j * e + self.key_size]
                if k < lo:
                    continue
                if k > hi:
                    break
                out[k] = data[j * e + self.key_size : (j + 1) * e]
        return out

    def _table_get(self, info: TableInfo, key: bytes) -> bytes | None:
        if info.filter_address:
            # bloom check first: a negative skips the index+data reads
            # entirely (reference: src/lsm/bloom_filter.zig consulted in
            # lookup_from_levels_storage)
            if not filter_may_contain(
                self.grid.read_block(info.filter_address), key,
                version=info.filter_version,
            ):
                return None
        index = self.grid.read_block(info.index_address)
        # index payload: [addr u64][first_key key_size] per data block
        rec = 8 + self.key_size
        n = len(index) // rec
        lo, hi = 0, n - 1
        pos = 0
        while lo <= hi:  # last block whose first key <= key
            mid = (lo + hi) // 2
            first = index[mid * rec + 8 : mid * rec + 8 + self.key_size]
            if first <= key:
                pos = mid
                lo = mid + 1
            else:
                hi = mid - 1
        addr = int.from_bytes(index[pos * rec : pos * rec + 8], "little")
        data = self.grid.read_block(addr)
        e = self.entry_size
        lo, hi = 0, len(data) // e - 1
        while lo <= hi:
            mid = (lo + hi) // 2
            k = data[mid * e : mid * e + self.key_size]
            if k == key:
                return data[mid * e + self.key_size : (mid + 1) * e]
            if k < key:
                lo = mid + 1
            else:
                hi = mid - 1
        return None

    # -- flush / compaction (array-native: tables move through flush and
    # merge as packed np.uint8 [n, entry_size] matrices — the per-entry
    # Python streaming this replaces was 85% of a whole spill cycle) --

    def flush(self) -> None:
        """Make every pending write durable-visible in the levels."""
        self._settle()
        self._flush_memtable()

    def _flush_memtable(self) -> None:
        if not self.memtable:
            if self._compact_debt:
                self._compact_with_debt()
            return
        items = sorted(self.memtable.items())
        self.memtable = {}
        flat = b"".join(k + v for k, v in items)
        entries = np.frombuffer(flat, dtype=np.uint8).reshape(
            len(items), self.entry_size
        )
        info = self._write_table_arr(entries)
        self.levels[0].insert(0, info)
        self._log("i", 0, info)
        self._compact_with_debt()

    def _compact_with_debt(self) -> None:
        """Run compaction under the resume contract: if a merge read
        raises (faulted block awaiting peer repair), the debt flag stays
        set and the NEXT settle point re-runs compaction BEFORE any new
        allocation — so a heal-and-retry replica allocates grid blocks in
        the same order as a replica that never faulted."""
        self._compact_debt = True
        self._maybe_compact()
        self._compact_debt = False

    def put_array(self, keys: np.ndarray, values,
                  settle: bool = True) -> None:
        """Array-native bulk put: keys np.uint8 [n, key_size]; values
        np.uint8 [n, value_size] or ONE shared value (bytes) broadcast to
        every key (secondary-index presence bytes). The spill cycle's
        write path — no per-key Python objects anywhere.

        Arrays BUFFER in _pending and settle in bulk (one sort over many
        cycles' worth of entries, split into large tables); any read or
        flush settles first, so visibility is unchanged. settle=False
        defers even the size-threshold settle: the call then touches no
        grid state at all and CANNOT raise — the exactly-once building
        block for the spill cycle's fault-retry contract."""
        n = len(keys)
        if n == 0:
            return
        assert keys.shape == (n, self.key_size) and keys.dtype == np.uint8
        if self.memtable:
            # settle=False promises "touches no grid state, CANNOT raise";
            # flushing a memtable writes tables and runs compaction (both
            # can raise GridBlockCorrupt). A caller mixing put() with
            # put_array(settle=False) must fail loudly here rather than
            # silently breaking the spill job's exactly-once fault-retry
            # contract.
            assert settle, (
                "put_array(settle=False) requires an empty memtable: the "
                "no-raise guarantee cannot hold across a memtable flush"
            )
            self._flush_memtable()
        self._pending.append((keys, values))
        self._pending_rows += n
        if settle and self._pending_rows >= self.settle_max:
            self._settle()

    def _settle(self) -> None:
        with self.tracer.span("lsm.compact", rows=self._pending_rows), \
                self.metrics.histogram("lsm.compact_us").time():
            self._settle_inner()

    def _settle_inner(self) -> None:
        """Sort the accumulated put_array buffers into level-0 tables.
        Resume-safe: all level-0 tables land before compaction starts, so
        a compaction raise leaves every settled entry durable in the
        levels and sets _compact_debt for the retry."""
        if not self._pending:
            if self._compact_debt:
                self._compact_with_debt()
            return
        bufs, self._pending = self._pending, []
        n = self._pending_rows
        self._pending_rows = 0
        entries = np.empty((n, self.entry_size), dtype=np.uint8)
        at = 0
        for keys, values in bufs:
            k = len(keys)
            entries[at : at + k, : self.key_size] = keys
            if isinstance(values, (bytes, bytearray)):
                assert len(values) == self.value_size
                entries[at : at + k, self.key_size :] = np.frombuffer(
                    bytes(values), dtype=np.uint8
                )
            else:
                assert values.shape == (k, self.value_size)
                entries[at : at + k, self.key_size :] = values
            at += k
        order = np.lexsort(self._key_cols(entries))
        entries = entries[order]
        if n > 1:
            # duplicate keys across buffers: LAST wins (later input is
            # newer; stable lexsort preserved input order within runs)
            kw = entries[:, : self.key_size]
            last = np.empty(n, dtype=bool)
            last[-1] = True
            last[:-1] = np.any(kw[1:] != kw[:-1], axis=1)
            entries = entries[last]
        # ALL chunks land in level 0 before any compaction: a compaction
        # read can raise GridBlockCorrupt (faulted block awaiting repair),
        # and the caller's retry must find every settled entry durable in
        # the levels — compacting between chunks would lose the rest
        for start in range(0, len(entries), self.table_entries_max):
            chunk = entries[start : start + self.table_entries_max]
            info = self._write_table_arr(chunk)
            self.levels[0].insert(0, info)
            self._log("i", 0, info)
        self._compact_with_debt()

    def _log(self, op: str, level: int, info: TableInfo) -> None:
        if self.manifest_log is not None:
            self.manifest_log.append(self.tree_id, level, op, info)

    def _key_cols(self, entries: np.ndarray) -> tuple:
        """Sort columns for np.lexsort: the key bytes (big-endian
        comparable) packed into native u64 words, LEAST significant word
        first (lexsort's primary key is the last element). Right-padding
        with zeros preserves lexicographic order for equal-length keys."""
        k = self.key_size
        nw = (k + 7) // 8
        n = len(entries)
        if k == nw * 8:
            padded = np.ascontiguousarray(entries[:, :k])
        else:
            padded = np.zeros((n, nw * 8), dtype=np.uint8)
            padded[:, :k] = entries[:, :k]
        words = padded.view(">u8").astype(np.uint64)
        return tuple(words[:, w] for w in range(nw - 1, -1, -1))

    def _write_table_arr(self, entries: np.ndarray) -> TableInfo:
        """One immutable on-disk table from sorted packed entries."""
        n = len(entries)
        assert n > 0
        epb = self.entries_per_block
        index = bytearray()
        flat = entries.tobytes()
        row = self.entry_size
        for i in range(0, n, epb):
            payload = flat[i * row : min(i + epb, n) * row]
            addr = self.grid.create_block(payload)
            index += addr.to_bytes(8, "little") + flat[
                i * row : i * row + self.key_size
            ]
        index_address = self.grid.create_block(bytes(index))
        filter_address = (
            self.grid.create_block(
                build_filter(entries[:, : self.key_size], n)
            )
            if self.filters else 0
        )
        return TableInfo(
            index_address=index_address,
            key_min=flat[: self.key_size],
            key_max=flat[(n - 1) * row : (n - 1) * row + self.key_size],
            entry_count=n,
            filter_address=filter_address,
            filter_version=1,
        )

    def _level_budget(self, level: int) -> int:
        return LEVEL0_TABLES_MAX * (GROWTH_FACTOR ** level)

    def _maybe_compact(self) -> None:
        """At most ONE paced table merge per over-budget level per call
        (the half-bar analog); a 2x-budget backpressure loop bounds the
        worst case (reference paces compaction so a level can never run
        away, src/lsm/compaction.zig:1-32)."""
        for level in range(len(self.levels)):
            budget = self._level_budget(level)
            if len(self.levels[level]) > budget:
                self._compact_one(level)
            while len(self.levels[level]) > 2 * budget:
                self._compact_one(level)
        from tigerbeetle_tpu import constants

        if constants.VERIFY:
            self.verify_levels()

    def verify_levels(self) -> None:
        """Intensive-tier audit (constants.VERIFY; reference
        src/constants.zig:592): every level >= 1 holds DISJOINT tables
        sorted by key range, and every table's bounds are ordered."""
        for level, tables in enumerate(self.levels):
            for info in tables:
                assert info.key_min <= info.key_max, (
                    f"L{level}: inverted table bounds"
                )
                assert info.entry_count > 0, f"L{level}: empty table"
            if level == 0:
                continue
            for a, b in zip(tables, tables[1:]):
                assert a.key_max < b.key_min, (
                    f"L{level}: overlapping/unsorted tables "
                    f"({a.key_max.hex()} !< {b.key_min.hex()})"
                )

    def _compact_one(self, level: int) -> None:
        """Merge ONE victim table from `level` with the intersecting tables
        of `level+1`: a VECTORIZED k-way merge — victim + intersecting run
        load as packed matrices, one stable lexsort orders them (victim
        rows first, so newest wins on equal keys), a shifted-compare mask
        dedups, tombstones drop at the bottom, and the result splits into
        bounded output tables. Host memory is O(victim + intersecting run)
        <= (1 + growth) tables — traded up from the old streaming merge's
        O(block) bound, which cost a Python iteration per entry and
        dominated entire spill cycles (reference streams because servers
        are memory-constrained, src/lsm/compaction.zig:1-32; this host is
        not, and the bench bills the difference)."""
        if level + 1 >= len(self.levels):
            self.levels.append([])
        src, dst = self.levels[level], self.levels[level + 1]
        if level == 0:
            cur = len(src) - 1  # oldest level-0 table
        else:
            cur = self._compact_cursor.get(level, 0) % len(src)
        victim = src[cur]  # peeked, NOT popped: reads below may raise
        # intersecting run in the (sorted, disjoint) destination level
        lo_i = 0
        while lo_i < len(dst) and dst[lo_i].key_max < victim.key_min:
            lo_i += 1
        hi_i = lo_i
        while hi_i < len(dst) and dst[hi_i].key_min <= victim.key_max:
            hi_i += 1
        olds = dst[lo_i:hi_i]
        bottom = (
            level + 1 == len(self.levels) - 1
            or all(not lvl for lvl in self.levels[level + 2 :])
        )

        if not olds:
            # disjoint victim: MOVE the table down — no read, no rewrite,
            # no grid churn (reference: src/lsm/compaction.zig move_table).
            # Ascending-key trees (object/posted trees: timestamp keys)
            # take this path almost every time, so their spill write cost
            # is one table write total.
            src.pop(cur)
            if level != 0:
                self._compact_cursor[level] = cur
            self._log("r", level, victim)
            self._log("i", level + 1, victim)
            self.levels[level + 1] = dst[:lo_i] + [victim] + dst[lo_i:]
            return

        # read EVERY merge input before touching the level lists: a read
        # of a faulted block raises GridBlockCorrupt, the replica repairs
        # it from a peer and retries — the tree must still hold all data.
        # Addresses are captured at read time so the releases below never
        # re-read (a re-read could raise AFTER the lists were mutated).
        inputs = [self._read_table_arr(t) for t in [victim, *olds]]
        src.pop(cur)
        if level != 0:
            self._compact_cursor[level] = cur  # next table shifts into place
        merged = np.concatenate([arr for arr, _ in inputs])
        order = np.lexsort(self._key_cols(merged))
        merged = merged[order]
        n = len(merged)
        keep = np.ones(n, dtype=bool)
        if n > 1:
            kw = merged[:, : self.key_size]
            # stable sort put the victim's (newer) row first in each equal-
            # key run: keep the FIRST of each run
            keep[1:] = np.any(kw[1:] != kw[:-1], axis=1)
        if bottom:
            keep &= ~np.all(
                merged[:, self.key_size :] == np.uint8(0xFF), axis=1
            )
        merged = merged[keep]

        out: list[TableInfo] = []
        for start in range(0, len(merged), self.table_entries_max):
            out.append(
                self._write_table_arr(
                    merged[start : start + self.table_entries_max]
                )
            )
        for (_, addrs), info in zip(inputs[1:], olds):
            self._release_table(info, addrs)
            self._log("r", level + 1, info)
        self._release_table(victim, inputs[0][1])
        self._log("r", level, victim)
        for info in out:
            self._log("i", level + 1, info)
        self.levels[level + 1] = dst[:lo_i] + out + dst[hi_i:]

    def _read_table_arr(
        self, info: TableInfo
    ) -> tuple[np.ndarray, list[int]]:
        """One table's entries as a packed np.uint8 [n, entry_size] matrix
        (the merge input form), plus its data-block addresses (so the
        caller can release the table without re-reading the index)."""
        index = self.grid.read_block(info.index_address)
        rec = 8 + self.key_size
        addrs = [
            int.from_bytes(index[i * rec : i * rec + 8], "little")
            for i in range(len(index) // rec)
        ]
        flat = b"".join(self.grid.read_block(a) for a in addrs)
        # read-only view is fine: merge inputs only flow into concatenate/
        # fancy-indexing, which allocate fresh output arrays
        return np.frombuffer(flat, dtype=np.uint8).reshape(
            -1, self.entry_size
        ), addrs

    def _release_table(self, info: TableInfo, addrs: list[int]) -> None:
        """Release a table's blocks from captured addresses — no reads."""
        for a in addrs:
            self.grid.release(a)
        self.grid.release(info.index_address)
        if info.filter_address:
            self.grid.release(info.filter_address)

    # -- checkpoint (persisted via the ManifestLog, lsm/manifest_log.py) --

    def live_tables(self) -> list:
        """(tree_id, level, info) of every live table — the manifest log's
        compaction snapshot input. Level 0 is emitted OLDEST-FIRST: the
        log's restore replays events chronologically and rebuilds level 0
        newest-first by reversing, so snapshot events must read like the
        original insert order."""
        out = [(self.tree_id, 0, info) for info in reversed(self.levels[0])]
        for level, tables in enumerate(self.levels[1:], start=1):
            out += [(self.tree_id, level, info) for info in tables]
        return out

    def restore_levels(self, per_level: dict[int, list[TableInfo]]) -> None:
        """Adopt levels replayed from the manifest log."""
        n = max(per_level, default=0) + 1
        self.levels = [per_level.get(i, []) for i in range(max(n, 1))]
        self.memtable = {}
        self._pending = []
        self._pending_rows = 0
        self._compact_debt = False
        self._compact_cursor = {}
