"""ctypes binding to the native C++ runtime library (native/libtb_native.so).

The compute path is JAX/XLA; the runtime around it — checksums, durable
sector IO — is native C++ (the reference's analogs are Zig:
src/vsr/checksum.zig, src/storage.zig). The library is built on demand with
the baked-in g++ (no pip/pybind11 — plain ctypes over a C ABI).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libtb_native.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None


def _build() -> None:
    srcs = [
        os.path.join(_NATIVE_DIR, s)
        for s in ("aegis.cc", "storage.cc", "tb_client.cc", "ledger.cc")
    ]
    if os.path.exists(_LIB_PATH) and all(
        os.path.getmtime(_LIB_PATH) >= os.path.getmtime(s) for s in srcs
    ):
        return
    subprocess.run(
        ["make", "-s", "libtb_native.so"], cwd=_NATIVE_DIR, check=True
    )


def lib() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is None:
            _build()
            l = ctypes.CDLL(_LIB_PATH)
            l.tb_checksum.argtypes = [
                ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p
            ]
            l.tb_checksum.restype = None
            l.tb_storage_open.argtypes = [
                ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int
            ]
            l.tb_storage_open.restype = ctypes.c_int
            l.tb_storage_close.argtypes = [ctypes.c_int]
            l.tb_storage_close.restype = ctypes.c_int
            for fn in (l.tb_storage_write, l.tb_storage_read):
                fn.argtypes = [
                    ctypes.c_int, ctypes.c_uint64, ctypes.c_char_p,
                    ctypes.c_uint64,
                ]
                fn.restype = ctypes.c_int
            l.tb_storage_sync.argtypes = [ctypes.c_int]
            l.tb_storage_sync.restype = ctypes.c_int
            # native ledger engine (native/ledger.cc)
            l.tb_ledger_new.argtypes = [ctypes.c_int, ctypes.c_int]
            l.tb_ledger_new.restype = ctypes.c_void_p
            l.tb_ledger_free.argtypes = [ctypes.c_void_p]
            l.tb_ledger_free.restype = None
            l.tb_ledger_execute.argtypes = [
                ctypes.c_void_p, ctypes.c_uint8, ctypes.c_char_p,
                ctypes.c_uint32, ctypes.c_uint64, ctypes.c_void_p,
            ]
            l.tb_ledger_execute.restype = ctypes.c_int64
            l.tb_ledger_execute_group.argtypes = [
                ctypes.c_void_p, ctypes.c_uint8, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint32,
                ctypes.c_void_p, ctypes.c_void_p,
            ]
            l.tb_ledger_execute_group.restype = ctypes.c_int64
            l.tb_ledger_fingerprint.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p
            ]
            l.tb_ledger_fingerprint.restype = None
            l.tb_ledger_lookup.argtypes = [
                ctypes.c_void_p, ctypes.c_uint8, ctypes.c_char_p,
                ctypes.c_uint32, ctypes.c_void_p,
            ]
            l.tb_ledger_lookup.restype = ctypes.c_uint64
            l.tb_ledger_counts.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
            l.tb_ledger_counts.restype = None
            l.tb_ledger_snapshot_size.argtypes = [ctypes.c_void_p]
            l.tb_ledger_snapshot_size.restype = ctypes.c_uint64
            l.tb_ledger_snapshot.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
            l.tb_ledger_snapshot.restype = None
            l.tb_ledger_restore.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64
            ]
            l.tb_ledger_restore.restype = ctypes.c_int
            _lib = l
    return _lib


def checksum(data: bytes) -> int:
    """AEGIS-128L MAC checksum -> u128 (reference: src/vsr/checksum.zig:53).
    Every header, body, and block is guarded by this."""
    out = ctypes.create_string_buffer(16)
    lib().tb_checksum(bytes(data), len(data), out)
    return int.from_bytes(out.raw, "little")


CHECKSUM_BODY_EMPTY = 0x49F174618255402DE6E7E3C40D60CC83
"""checksum(b"") — pinned by the reference (src/vsr.zig:238)."""
