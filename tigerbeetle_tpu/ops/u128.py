"""Exact u128 arithmetic as two u64 limbs (lo, hi) on device.

The reference's amounts/balances are u128 with precise overflow semantics
(reference: src/state_machine.zig:848-862, src/tigerbeetle.zig:7-40). TPUs have
no native 128-bit integers, so every u128 is a pair of u64 arrays; all helpers
are shape-polymorphic (work on scalars and batches alike) and return explicit
carry/borrow bits where overflow matters.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

U64 = jnp.uint64
U32 = jnp.uint32

# numpy, not jnp: captured concrete jax arrays poison dispatch (see
# ops/hashtable.py note).
_ONE = np.uint64(1)
_ZERO = np.uint64(0)


def add(a_lo, a_hi, b_lo, b_hi):
    """(a + b) mod 2^128 with carry-out. Returns (lo, hi, carry_out bool)."""
    lo = a_lo + b_lo
    c0 = lo < a_lo
    hi0 = a_hi + b_hi
    c1 = hi0 < a_hi
    hi = hi0 + c0.astype(U64)
    c2 = hi < hi0
    return lo, hi, c1 | c2


def add_u64(a_lo, a_hi, b):
    """(a + b) for u64 b, with carry-out."""
    return add(a_lo, a_hi, b, jnp.zeros_like(b))


def sub(a_lo, a_hi, b_lo, b_hi):
    """(a - b) mod 2^128 with borrow-out (True iff a < b)."""
    lo = a_lo - b_lo
    brw0 = a_lo < b_lo
    hi0 = a_hi - b_hi
    brw1 = a_hi < b_hi
    hi = hi0 - brw0.astype(U64)
    brw2 = hi > hi0  # wrapped below zero
    return lo, hi, brw1 | brw2


def sat_sub(a_lo, a_hi, b_lo, b_hi):
    """max(0, a - b) (saturating subtract)."""
    lo, hi, brw = sub(a_lo, a_hi, b_lo, b_hi)
    return jnp.where(brw, _ZERO, lo), jnp.where(brw, _ZERO, hi)


def eq(a_lo, a_hi, b_lo, b_hi):
    return (a_lo == b_lo) & (a_hi == b_hi)


def lt(a_lo, a_hi, b_lo, b_hi):
    return (a_hi < b_hi) | ((a_hi == b_hi) & (a_lo < b_lo))


def gt(a_lo, a_hi, b_lo, b_hi):
    return lt(b_lo, b_hi, a_lo, a_hi)


def le(a_lo, a_hi, b_lo, b_hi):
    return ~gt(a_lo, a_hi, b_lo, b_hi)


def is_zero(a_lo, a_hi):
    return (a_lo == _ZERO) & (a_hi == _ZERO)


def is_max(a_lo, a_hi):
    m = jnp.uint64(0xFFFFFFFFFFFFFFFF)
    return (a_lo == m) & (a_hi == m)


def min_(a_lo, a_hi, b_lo, b_hi):
    a_less = lt(a_lo, a_hi, b_lo, b_hi)
    return jnp.where(a_less, a_lo, b_lo), jnp.where(a_less, a_hi, b_hi)


def select(pred, a_lo, a_hi, b_lo, b_hi):
    return jnp.where(pred, a_lo, b_lo), jnp.where(pred, a_hi, b_hi)


def sum_overflows(a_lo, a_hi, b_lo, b_hi):
    """reference: src/state_machine.zig:1152-1157 (u128 instantiation)."""
    _, _, carry = add(a_lo, a_hi, b_lo, b_hi)
    return carry


def sum_overflows_u64(a, b):
    """reference: src/state_machine.zig:1152-1157 (u64 instantiation)."""
    return (a + b) < a
