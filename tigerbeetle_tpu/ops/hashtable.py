"""HBM-resident hash tables over 128-byte wire-layout rows — straight-line probes.

This is the TPU-native replacement for the reference's Groove object store +
CacheMap (reference: src/lsm/groove.zig:602-760, src/lsm/cache_map.zig): the
full working set lives in HBM as a single [capacity + 1, 32] u32 array per
table, each row being the object's 128-byte little-endian wire format
(reference: src/tigerbeetle.zig:7-104) — so a host batch uploads as one
bitcast and a probe fetches a whole object in one gather.

Design constraints discovered on the target stack (and why this file has NO
lax.while_loop / lax.cond / data-dependent trip counts):

- Plain gathers/scatters over multi-GiB tables are fast (~30us for an
  8k-lane batch), including window gathers of [B, W, 4] probe keys.
- A gather INSIDE a while_loop/scan body permanently degrades the process's
  dispatch path (every subsequent kernel launch ~12ms instead of ~30us) —
  measured, reproducible, and fatal for throughput. Data-dependent probe
  continuation loops are therefore banned from every device kernel.

So probing is **double hashing with a fixed probe window**: probe j visits
`(h1(key) + j * step(key)) & mask` with `step` odd (coprime to the power-of-2
capacity, so the sequence visits every slot). All W probes for all lanes are
fetched in ONE window gather and resolved branch-free. Double hashing (vs
linear probing) makes chain-length tails geometric with NO clustering:
P(chain >= W) ~ alpha^W, so with the enforced load factor alpha <= 1/2
(constants.LOAD_FACTOR_*) and W = 32, an unresolved probe is a ~2^-32 event
per op. Unresolved lanes are reported to the caller, which must abort the
whole batch (no partial application) and raise a sticky fault — see
models/ledger.py's fault protocol.

Key encoding in row words 0..3 (the id):
- empty slot:     all four words 0  (valid ids are never 0)
- tombstone slot: all four words 0xFFFFFFFF  (valid ids are never u128 max;
  both invariants enforced by id_must_not_be_zero / id_must_not_be_int_max,
  reference: src/tigerbeetle.zig:118-121, 160-163)
Tombstones arise only from linked-chain rollback deletions: probes skip them
(only an EMPTY slot terminates a chain), inserts reuse them.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

U64 = jnp.uint64
U32 = jnp.uint32
I32 = jnp.int32

# NOTE: module-level constants MUST be numpy (not jnp): a jitted function
# that captures a concrete jax array permanently degrades the process's
# dispatch path on the tunneled-TPU runtime (measured: every subsequent
# kernel launch ~12 ms instead of ~30 us). numpy scalars embed as XLA
# literals instead of captured device buffers.
TOMB_WORD = np.uint32(0xFFFFFFFF)
CLAIM_FREE = np.uint32(0xFFFFFFFF)

_MIX = np.uint64(0x9E3779B97F4A7C15)
_MIX2 = np.uint64(0xD1B54A32D192ED03)

# Fixed probe windows. Batched table ops probe WINDOW slots in one gather;
# scalar probes (the serial scan kernel) use the longer WINDOW_SCALAR prefix
# of the same probe sequence — a longer window is near-free for one lane and
# makes a serial-tier unresolved probe (which cannot be rolled back mid-scan)
# a ~2^-64 event.
WINDOW = 32
WINDOW_SCALAR = 64


def key4_of_rows(rows):
    """The id words of wire rows (works for [N, 32] and [32])."""
    return rows[..., :4]


def _fold64(key4):
    k = key4.astype(U64)
    lo = k[..., 0] | (k[..., 1] << jnp.uint64(32))
    hi = k[..., 2] | (k[..., 3] << jnp.uint64(32))
    return lo, hi


def hash_key4(key4, cap_log2: int):
    """splitmix64 finalizer over both id limbs -> base slot in [0, 2^cap_log2)."""
    lo, hi = _fold64(key4)
    x = lo ^ (hi * _MIX)
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    x = x ^ (x >> jnp.uint64(31))
    return (x & jnp.uint64((1 << cap_log2) - 1)).astype(I32)


def probe_step(key4, cap_log2: int):
    """Second, independent hash -> ODD probe stride (odd strides are units
    mod 2^cap_log2, so the probe sequence is a full cycle)."""
    lo, hi = _fold64(key4)
    x = (lo ^ jnp.uint64(0x6A09E667F3BCC909)) * _MIX2
    x = x ^ (hi * _MIX2) ^ (x >> jnp.uint64(31))
    x = (x ^ (x >> jnp.uint64(29))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = x ^ (x >> jnp.uint64(32))
    return ((x & jnp.uint64((1 << cap_log2) - 1)) | jnp.uint64(1)).astype(I32)


def probe_positions(key4, cap_log2: int, window: int):
    """[..., window] i32 slots: the first `window` probes of key4's sequence."""
    mask = jnp.int32((1 << cap_log2) - 1)
    base = hash_key4(key4, cap_log2)
    step = probe_step(key4, cap_log2)
    j = jnp.arange(window, dtype=I32)
    return (base[..., None] + j * step[..., None]) & mask


def _is_empty(k4):
    return jnp.all(k4 == 0, axis=-1)


def _is_tomb(k4):
    return jnp.all(k4 == TOMB_WORD, axis=-1)


def occupied_mask(rows):
    """Per-slot liveness of a [N, 32] row table: neither empty nor
    tombstone (THE definition — spill scans and query filter scans must
    agree bit-for-bit with the probe kernels' slot encoding)."""
    k4 = rows[..., :4]
    return ~_is_empty(k4) & ~_is_tomb(k4)


def lookup(key4, rows, cap_log2: int, window: int = WINDOW):
    """Probe for key4 ([..., 4] u32; batched or scalar). ONE window gather,
    branch-free resolve. Returns (slot i32, found bool, resolved bool):

    - found: the key is in the table; `slot` is its row.
    - not found but resolved: an EMPTY slot terminated the chain before any
      hit; `slot` is the first free (empty or tombstone) probe position —
      the insert target for this key.
    - not resolved (~2^-window per op at load <= 1/2): no hit and no empty
      within the window; `slot` is arbitrary. The CALLER must treat the
      whole batch as failed (fault protocol) — results are unsound.

    Keys that are themselves empty/tomb-encoded (all-0s / all-1s ids) are
    never reported found; they resolve like ordinary absent keys.
    """
    pos = probe_positions(key4, cap_log2, window)  # [..., W]
    k4 = rows[pos, :4]  # [..., W, 4]
    key_probeable = ~_is_empty(key4) & ~_is_tomb(key4)
    hit = jnp.all(k4 == key4[..., None, :], axis=-1) & key_probeable[..., None]
    empty = _is_empty(k4)
    free = empty | _is_tomb(k4)

    j = jnp.arange(window, dtype=I32)
    big = jnp.int32(window)
    hit_j = jnp.min(jnp.where(hit, j, big), axis=-1)
    empty_j = jnp.min(jnp.where(empty, j, big), axis=-1)
    free_j = jnp.min(jnp.where(free, j, big), axis=-1)

    found = hit_j < empty_j  # a hit before the chain terminator
    resolved = found | (empty_j < big)
    sel = jnp.where(found, hit_j, jnp.minimum(free_j, big - 1))
    slot = jnp.take_along_axis(pos, sel[..., None], axis=-1)[..., 0]
    return slot, found, resolved


def claim_slots(key4, active, rows, claim, cap_log2: int,
                window: int = WINDOW, rounds: int = 4):
    """Claim one distinct free slot per active lane for batch-unique, absent
    keys (the parallel-insert slot assignment). Pure claim phase: the rows
    table is NOT written — the caller scatters the rows after gating on
    `resolved` (so an aborting batch leaves the table untouched).

    Returns (slots i32 [B], claim', resolved bool [B]). `slots` is the dump
    slot (capacity) for inactive or unresolved lanes. `claim` is the
    persistent [capacity+1] u32 scratch column (CLAIM_FREE everywhere between
    batches); claims are held across rounds as in-batch occupancy and all
    released before return.

    Races between lanes probing the same slot are resolved deterministically
    by scatter-min of the lane index; a losing lane's next round recomputes
    its first free-and-unclaimed probe position (the lost slot is now
    claimed, so it is skipped automatically). With double hashing, two lanes
    share more than one probe position only on a ~2^-64 hash collision, so
    `rounds` bounds the CONTENTION depth, not chain length; unresolved lanes
    after `rounds` rounds are reported, not retried.
    """
    cap = 1 << cap_log2
    dump = jnp.int32(cap)
    B = key4.shape[0]
    lanes = jnp.arange(B, dtype=U32)

    pos = probe_positions(key4, cap_log2, window)  # [B, W]
    k4 = rows[pos, :4]
    table_free = _is_empty(k4) | _is_tomb(k4)  # [B, W] — static during claims

    j = jnp.arange(window, dtype=I32)
    big = jnp.int32(window)

    won = jnp.zeros(B, dtype=bool)
    slot = jnp.full(B, dump, dtype=I32)
    for _ in range(rounds):
        clm_w = claim[pos]  # [B, W] — refreshed each round
        cand_j = jnp.min(
            jnp.where(table_free & (clm_w == CLAIM_FREE), j, big), axis=-1
        )
        has_cand = cand_j < big
        cand = jnp.take_along_axis(
            pos, jnp.minimum(cand_j, big - 1)[:, None], axis=-1
        )[:, 0]
        want = active & ~won & has_cand
        tgt = jnp.where(want, cand, dump)
        claim = claim.at[tgt].min(lanes)
        newly = want & (claim[cand] == lanes)
        slot = jnp.where(newly, cand, slot)
        won = won | newly

    resolved = won | ~active
    # Release every claim this batch made: winners' slots + the dump slot
    # (losing lanes' scatter-min landed on slots that some lane won, or on
    # the dump slot — both covered).
    claim = claim.at[slot].set(CLAIM_FREE).at[dump].set(CLAIM_FREE)
    return slot, claim, resolved


def probe_free(key4, rows, cap_log2: int, window: int = WINDOW_SCALAR):
    """First free (empty or tombstone) probe position for a key known to be
    absent (the serial scan kernel's insert target; it masks its own writes).
    Returns (slot, ok). One window gather, no loops."""
    pos = probe_positions(key4, cap_log2, window)
    k4 = rows[pos, :4]
    free = _is_empty(k4) | _is_tomb(k4)
    j = jnp.arange(window, dtype=I32)
    big = jnp.int32(window)
    free_j = jnp.min(jnp.where(free, j, big), axis=-1)
    ok = free_j < big
    sel = jnp.minimum(free_j, big - 1)
    slot = jnp.take_along_axis(pos, sel[..., None], axis=-1)[..., 0]
    return slot, ok


def insert_rows(row32, active, rows, claim, cap_log2: int,
                window: int = WINDOW, rounds: int = 4):
    """claim_slots + row scatter in one call (convenience for callers that
    gate on `resolved` themselves AFTER the write — e.g. test harnesses).
    Production kernels should use claim_slots and scatter after gating.

    Returns (slots, rows', claim', resolved)."""
    key4 = key4_of_rows(row32)
    slots, claim, resolved = claim_slots(
        key4, active, rows, claim, cap_log2, window=window, rounds=rounds
    )
    rows = rows.at[slots].set(row32)
    return slots, rows, claim, resolved
