"""HBM-resident open-addressing hash tables over 128-byte wire-layout rows.

This is the TPU-native replacement for the reference's Groove object store +
CacheMap (reference: src/lsm/groove.zig:602-760, src/lsm/cache_map.zig): the
full working set lives in HBM as a single [capacity + 1, 32] u32 array per
table, each row being the object's 128-byte little-endian wire format
(reference: src/tigerbeetle.zig:7-104) — so a host batch uploads as one
bitcast and a probe fetches a whole object in one gather.

Why u32 rows: on TPU, XLA lowers 64-bit gathers/scatters to per-index scalar
DMAs (~100us per op for an 8k batch), while u32 row gathers vectorize
(~10us). All storage is u32; arithmetic widens to u64 limbs after gathering
(elementwise widening is cheap).

Slot `capacity` is a write dump for masked scatters (never read). Probing is
linear with a batched while_loop. Key encoding in row words 0..3 (the id):
- empty slot:     all four words 0  (valid ids are never 0)
- tombstone slot: all four words 0xFFFFFFFF  (valid ids are never u128 max;
  both invariants enforced by id_must_not_be_zero / id_must_not_be_int_max,
  reference: src/tigerbeetle.zig:118-121, 160-163)
Tombstones arise only from linked-chain rollback deletions; lookups skip
them, inserts reuse them.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

U64 = jnp.uint64
U32 = jnp.uint32
I32 = jnp.int32

TOMB_WORD = jnp.uint32(0xFFFFFFFF)
CLAIM_FREE = jnp.uint32(0xFFFFFFFF)

_MIX = jnp.uint64(0x9E3779B97F4A7C15)


def key4_of_rows(rows):
    """The id words of wire rows (works for [N, 32] and [32])."""
    return rows[..., :4]


def hash_key4(key4, cap_log2: int):
    """splitmix64 finalizer over both id limbs -> slot in [0, 2^cap_log2)."""
    k = key4.astype(U64)
    lo = k[..., 0] | (k[..., 1] << jnp.uint64(32))
    hi = k[..., 2] | (k[..., 3] << jnp.uint64(32))
    x = lo ^ (hi * _MIX)
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    x = x ^ (x >> jnp.uint64(31))
    return (x & jnp.uint64((1 << cap_log2) - 1)).astype(I32)


def _key_eq(a4, b4):
    return jnp.all(a4 == b4, axis=-1)


def _is_empty(k4):
    return jnp.all(k4 == 0, axis=-1)


def _is_tomb(k4):
    return jnp.all(k4 == TOMB_WORD, axis=-1)


LOOKUP_UNROLL = 8


def lookup(key4, rows, cap_log2: int, unroll: int = LOOKUP_UNROLL):
    """Batched (or scalar) probe. Returns (slot i32, found bool).

    The first `unroll` probe steps are straight-line code (a TPU while_loop
    costs ~0.3ms per iteration in scalar-core sync, so data-dependent trip
    counts are poison for the common case); a while_loop continuation runs
    under lax.cond only if some lane's chain is longer — vanishingly rare at
    the enforced <= 7/8 load factor.

    When not found, `slot` is the first empty slot of the probe chain (or an
    arbitrary probed slot if the scan hit the probe bound) — callers must
    gate on `found`.
    """
    mask = jnp.int32((1 << cap_log2) - 1)
    idx = hash_key4(key4, cap_log2)
    key_probeable = ~_is_empty(key4) & ~_is_tomb(key4)
    done = jnp.zeros(idx.shape, dtype=bool)
    found = jnp.zeros(idx.shape, dtype=bool)

    def probe_once(idx, done, found):
        k4 = rows[idx, :4]  # key words only — 16B per probed slot
        hit = _key_eq(k4, key4) & key_probeable
        empty = _is_empty(k4)
        newly = ~done & (hit | empty)
        found = jnp.where(newly, hit, found)
        done = done | newly
        idx = jnp.where(done, idx, (idx + 1) & mask)
        return idx, done, found

    for _ in range(min(unroll, 1 << cap_log2)):
        idx, done, found = probe_once(idx, done, found)

    def continuation(carry):
        def cond(c):
            _, done, _, steps = c
            return (~jnp.all(done)) & (steps <= mask)

        def body(c):
            idx, done, found, steps = c
            idx, done, found = probe_once(idx, done, found)
            return idx, done, found, steps + 1

        idx, done, found, _ = jax.lax.while_loop(
            cond, body, (*carry, jnp.int32(0))
        )
        return idx, done, found

    idx, _, found = jax.lax.cond(
        jnp.all(done), lambda c: c, continuation, (idx, done, found)
    )
    return idx, found


def insert_rows(row32, active, rows, claim, cap_log2: int):
    """Claim one distinct slot per active lane and write the full 32-word row
    there, for batch-unique, absent keys (id = row words 0..3).

    Returns (slots i32 [B] — dump slot for inactive lanes, rows', claim').
    Probe races between lanes are resolved deterministically by scatter-min of
    the lane index into the persistent `claim` scratch column (reset to
    CLAIM_FREE before return). Losing lanes observe the winner's key on the
    next iteration and probe on.
    """
    cap = 1 << cap_log2
    mask = jnp.int32(cap - 1)
    dump = jnp.int32(cap)
    B = row32.shape[0]
    lanes = jnp.arange(B, dtype=U32)
    key4 = key4_of_rows(row32)
    idx = hash_key4(key4, cap_log2)
    done0 = ~active

    # Claims are HELD across rounds as in-batch occupancy (claim[slot] != FREE
    # means "taken by this batch"), so the table itself is never written during
    # probing — each round is just three cheap u32 gathers/scatters. Every
    # claimed slot has a winner, so the final reset at `slots` frees them all.
    def claim_once(idx, done, clm):
        k4 = rows[idx, :4]
        table_free = _is_empty(k4) | _is_tomb(k4)
        want = ~done & table_free & (clm[idx] == CLAIM_FREE)
        clm = clm.at[jnp.where(want, idx, dump)].min(lanes)
        won = want & (clm[idx] == lanes)
        done = done | won
        idx = jnp.where(done, idx, (idx + 1) & mask)
        return idx, done, clm

    idx, done, clm = (idx, done0, claim)
    for _ in range(min(LOOKUP_UNROLL, 1 << cap_log2)):
        idx, done, clm = claim_once(idx, done, clm)

    def continuation(carry):
        def cond(c):
            _, done, _, steps = c
            return (~jnp.all(done)) & (steps <= mask)

        def body(c):
            idx, done, clm, steps = c
            idx, done, clm = claim_once(idx, done, clm)
            return idx, done, clm, steps + 1

        idx, done, clm, _ = jax.lax.while_loop(cond, body, (*carry, jnp.int32(0)))
        return idx, done, clm

    idx, done, clm = jax.lax.cond(
        jnp.all(done), lambda c: c, continuation, (idx, done, clm)
    )
    slots = jnp.where(active & done, idx, dump)
    rows = rows.at[slots].set(row32)
    # Reset won slots + the dump slot (non-want lanes min-scatter there).
    claim = clm.at[slots].set(CLAIM_FREE).at[dump].set(CLAIM_FREE)
    return slots, rows, claim


def probe_free_scalar(key4, rows, cap_log2: int):
    """Read-only scalar probe to the first free (empty or tombstone) slot of
    the key's probe chain (for the serial scan kernel, which masks its own
    writes). The key must be absent from the table."""
    mask = jnp.int32((1 << cap_log2) - 1)
    idx = hash_key4(key4, cap_log2)

    def cond(carry):
        idx, steps = carry
        k4 = key4_of_rows(rows[idx])
        free = _is_empty(k4) | _is_tomb(k4)
        return (~free) & (steps <= mask)

    def body(carry):
        idx, steps = carry
        return (idx + 1) & mask, steps + 1

    idx, _ = jax.lax.while_loop(cond, body, (idx, jnp.int32(0)))
    return idx
