"""HBM-resident open-addressing hash tables keyed by u128 ids.

This is the TPU-native replacement for the reference's Groove object store +
CacheMap (reference: src/lsm/groove.zig:602-760, src/lsm/cache_map.zig): instead
of an LSM-backed cache with async prefetch, the full working set lives in HBM
as struct-of-arrays columns over `capacity + 1` slots. Slot `capacity` is a
write dump for masked scatters (predicated lanes write there and the row is
never read). Probing is linear with a batched while_loop: every lane gathers
its candidate slot each iteration, so a batch of 8190 lookups costs
O(max probe chain) gathers of the whole batch, not O(batch) serial probes.

Key encoding:
- empty slot:      key == (0, 0)        (valid ids are never 0)
- tombstone slot:  key == (2^64-1, 2^64-1)  (valid ids are never u128 max;
  both invariants are enforced by id_must_not_be_zero / id_must_not_be_int_max,
  reference: src/tigerbeetle.zig:118-121, 160-163)
Tombstones arise only from linked-chain rollback deletions; lookups skip them,
inserts reuse them.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

U64 = jnp.uint64
U32 = jnp.uint32
I32 = jnp.int32

EMPTY = jnp.uint64(0)
TOMB = jnp.uint64(0xFFFFFFFFFFFFFFFF)
CLAIM_FREE = jnp.uint32(0xFFFFFFFF)

_MIX = jnp.uint64(0x9E3779B97F4A7C15)


def hash_u128(key_lo, key_hi, cap_log2: int):
    """splitmix64 finalizer over a mix of both limbs -> slot in [0, 2^cap_log2)."""
    x = key_lo ^ (key_hi * _MIX)
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    x = x ^ (x >> jnp.uint64(31))
    return (x & jnp.uint64((1 << cap_log2) - 1)).astype(I32)


def lookup(key_lo, key_hi, tbl_key_lo, tbl_key_hi, cap_log2: int):
    """Batched (or scalar) probe. Returns (slot i32, found bool).

    When not found, `slot` is the first empty slot of the probe chain (or an
    arbitrary probed slot if the scan hit the probe bound) — callers must gate
    on `found` and use dedicated insertion for writes.
    """
    mask = jnp.int32((1 << cap_log2) - 1)
    idx = hash_u128(key_lo, key_hi, cap_log2)
    # A key equal to the empty/tombstone encodings must never "hit".
    key_probeable = ~((key_lo == EMPTY) & (key_hi == EMPTY)) & ~(
        (key_lo == TOMB) & (key_hi == TOMB)
    )
    done0 = jnp.zeros_like(key_probeable, dtype=bool) & False
    found0 = jnp.zeros_like(done0)
    steps0 = jnp.int32(0)

    def cond(carry):
        _, done, _, steps = carry
        return (~jnp.all(done)) & (steps <= mask)

    def body(carry):
        idx, done, found, steps = carry
        k_lo = tbl_key_lo[idx]
        k_hi = tbl_key_hi[idx]
        hit = (k_lo == key_lo) & (k_hi == key_hi) & key_probeable
        empty = (k_lo == EMPTY) & (k_hi == EMPTY)
        newly = ~done & (hit | empty)
        found = jnp.where(newly, hit, found)
        done = done | newly
        idx = jnp.where(done, idx, (idx + 1) & mask)
        return idx, done, found, steps + 1

    idx, _, found, _ = jax.lax.while_loop(cond, body, (idx, done0, found0, steps0))
    return idx, found


def insert_slots(key_lo, key_hi, active, tbl_key_lo, tbl_key_hi, claim, cap_log2: int):
    """Claim one distinct slot per active lane for batch-unique, absent keys.

    Returns (slots i32 [B] — dump slot for inactive lanes, tbl_key_lo',
    tbl_key_hi', claim'). Races between lanes probing the same slot are
    resolved deterministically by scatter-min of the lane index into the
    persistent `claim` scratch column (reset to CLAIM_FREE before return).
    Losing lanes observe the winner's key on the next iteration and probe on.
    """
    cap = 1 << cap_log2
    mask = jnp.int32(cap - 1)
    dump = jnp.int32(cap)
    lanes = jnp.arange(key_lo.shape[0], dtype=U32)
    idx = hash_u128(key_lo, key_hi, cap_log2)
    done0 = ~active
    steps0 = jnp.int32(0)

    def cond(carry):
        _, done, _, _, _, steps = carry
        return (~jnp.all(done)) & (steps <= mask)

    def body(carry):
        idx, done, tk_lo, tk_hi, clm, steps = carry
        k_lo = tk_lo[idx]
        k_hi = tk_hi[idx]
        free = ((k_lo == EMPTY) & (k_hi == EMPTY)) | ((k_lo == TOMB) & (k_hi == TOMB))
        want = ~done & free
        widx = jnp.where(want, idx, dump)
        clm = clm.at[widx].min(lanes)
        won = want & (clm[idx] == lanes)
        clm = clm.at[widx].set(CLAIM_FREE)
        sidx = jnp.where(won, idx, dump)
        tk_lo = tk_lo.at[sidx].set(jnp.where(won, key_lo, tk_lo[sidx]))
        tk_hi = tk_hi.at[sidx].set(jnp.where(won, key_hi, tk_hi[sidx]))
        done = done | won
        idx = jnp.where(done, idx, (idx + 1) & mask)
        return idx, done, tk_lo, tk_hi, clm, steps + 1

    idx, done, tbl_key_lo, tbl_key_hi, claim, _ = jax.lax.while_loop(
        cond, body, (idx, done0, tbl_key_lo, tbl_key_hi, claim, steps0)
    )
    slots = jnp.where(active & done, idx, dump)
    return slots, tbl_key_lo, tbl_key_hi, claim


def probe_free_scalar(key_lo, key_hi, tbl_key_lo, tbl_key_hi, cap_log2: int):
    """Read-only scalar probe to the first free (empty or tombstone) slot of
    the key's probe chain (for the serial scan kernel, which masks its own
    writes). The key must be absent from the table."""
    mask = jnp.int32((1 << cap_log2) - 1)
    idx = hash_u128(key_lo, key_hi, cap_log2)

    def cond(carry):
        idx, steps = carry
        k_lo = tbl_key_lo[idx]
        k_hi = tbl_key_hi[idx]
        free = ((k_lo == EMPTY) & (k_hi == EMPTY)) | ((k_lo == TOMB) & (k_hi == TOMB))
        return (~free) & (steps <= mask)

    def body(carry):
        idx, steps = carry
        return (idx + 1) & mask, steps + 1

    idx, _ = jax.lax.while_loop(cond, body, (idx, jnp.int32(0)))
    return idx
