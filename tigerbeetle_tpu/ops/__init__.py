from tigerbeetle_tpu.ops import hashtable, u128  # noqa: F401
