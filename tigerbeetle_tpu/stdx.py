"""Foundation data structures (reference: src/stdx.zig, src/ring_buffer.zig,
src/fifo.zig, src/iops.zig, src/ewah.zig — the statically-sized pools and
codecs everything above is built from)."""

from __future__ import annotations


class RingBuffer:
    """Fixed-capacity FIFO ring (reference: src/ring_buffer.zig). Pushing
    into a full ring is an error — static allocation discipline: capacity
    is sized exactly, never grown."""

    def __init__(self, capacity: int):
        assert capacity > 0
        self.buf: list = [None] * capacity
        self.capacity = capacity
        self.head = 0  # read position
        self.count = 0

    def __len__(self) -> int:
        return self.count

    @property
    def full(self) -> bool:
        return self.count == self.capacity

    def push(self, item) -> None:
        assert not self.full, "ring buffer full"
        self.buf[(self.head + self.count) % self.capacity] = item
        self.count += 1

    def pop(self):
        assert self.count > 0, "ring buffer empty"
        item = self.buf[self.head]
        self.buf[self.head] = None
        self.head = (self.head + 1) % self.capacity
        self.count -= 1
        return item

    def peek(self):
        assert self.count > 0
        return self.buf[self.head]

    def __iter__(self):
        for i in range(self.count):
            yield self.buf[(self.head + i) % self.capacity]


class FIFO:
    """Intrusive singly-linked FIFO (reference: src/fifo.zig): items carry
    their own `next` link, so push/pop never allocate."""

    def __init__(self):
        self.head = None
        self.tail = None
        self.count = 0

    def push(self, item) -> None:
        assert getattr(item, "next", None) is None, "item already queued"
        item.next = None
        if self.tail is None:
            self.head = self.tail = item
        else:
            self.tail.next = item
            self.tail = item
        self.count += 1

    def pop(self):
        item = self.head
        if item is None:
            return None
        self.head = item.next
        if self.head is None:
            self.tail = None
        item.next = None
        self.count -= 1
        return item

    def __len__(self) -> int:
        return self.count


class IOPS:
    """Fixed pool of in-flight operation slots tracked by a free bitset
    (reference: src/iops.zig:5): acquire returns a slot index or None when
    the pool is exhausted — backpressure, never allocation."""

    def __init__(self, size: int):
        assert 0 < size <= 64
        self.size = size
        self.free = (1 << size) - 1  # bit set = slot free

    def acquire(self) -> int | None:
        if self.free == 0:
            return None
        slot = (self.free & -self.free).bit_length() - 1
        self.free &= ~(1 << slot)
        return slot

    def release(self, slot: int) -> None:
        assert 0 <= slot < self.size
        assert not self.free & (1 << slot), "double release"
        self.free |= 1 << slot

    @property
    def executing(self) -> int:
        return self.size - bin(self.free).count("1")

    @property
    def available(self) -> int:
        return bin(self.free).count("1")


# ----------------------------------------------------------------------
# EWAH codec (reference: src/ewah.zig — word-aligned hybrid RLE over u64
# words; compresses the superblock's free-set bitset trailer)
# ----------------------------------------------------------------------

_ALL_ONES = (1 << 64) - 1
# marker layout (reference ewah.zig): bit 0 = uniform bit value,
# bits 1..32 = uniform word run length, bits 33..63 = literal word count
_RUN_MAX = (1 << 32) - 1
_LIT_MAX = (1 << 31) - 1


def ewah_encode(words: list[int]) -> bytes:
    """u64 word array -> EWAH bytes: [marker][literal words...] repeated."""
    out = bytearray()
    i = 0
    n = len(words)
    while i < n:
        # uniform run (all-zero or all-one words)
        bit = 0
        run = 0
        if words[i] in (0, _ALL_ONES):
            bit = 1 if words[i] == _ALL_ONES else 0
            target = _ALL_ONES if bit else 0
            while i < n and words[i] == target and run < _RUN_MAX:
                run += 1
                i += 1
        # literals until the next uniform word
        lit_start = i
        while (
            i < n
            and words[i] not in (0, _ALL_ONES)
            and (i - lit_start) < _LIT_MAX
        ):
            i += 1
        lit = i - lit_start
        marker = bit | (run << 1) | (lit << 33)
        out += marker.to_bytes(8, "little")
        for w in words[lit_start:i]:
            out += w.to_bytes(8, "little")
    return bytes(out)


def ewah_decode(data: bytes, words_count: int) -> list[int]:
    words: list[int] = []
    off = 0
    while off < len(data) and len(words) < words_count:
        if off + 8 > len(data):
            raise ValueError("ewah: truncated marker")
        marker = int.from_bytes(data[off : off + 8], "little")
        off += 8
        bit = marker & 1
        run = (marker >> 1) & _RUN_MAX
        lit = marker >> 33
        if len(words) + run + lit > words_count:
            # reject before materializing: a corrupt marker's 2^32-word run
            # must raise, not OOM
            raise ValueError("ewah: marker exceeds expected word count")
        words.extend([_ALL_ONES if bit else 0] * run)
        if off + 8 * lit > len(data):
            raise ValueError("ewah: truncated literals")
        for _ in range(lit):
            words.append(int.from_bytes(data[off : off + 8], "little"))
            off += 8
    if len(words) != words_count:
        raise ValueError(f"ewah: decoded {len(words)} of {words_count} words")
    return words
