"""Interactive client REPL (reference: src/tigerbeetle/repl.zig).

Statement grammar (the reference's):

  create_accounts  id=1 code=10 ledger=700, id=2 code=10 ledger=700;
  create_transfers id=1 debit_account_id=1 credit_account_id=2 amount=10
                   ledger=700 code=10 flags=linked|pending;
  lookup_accounts  id=1, id=2;
  lookup_transfers id=1;

Objects are comma-separated; a statement ends with `;`. Flag names join
with `|`. Drives the native session Client over the TCP message bus.
"""

from __future__ import annotations

import dataclasses
import random
import time

from tigerbeetle_tpu import types
from tigerbeetle_tpu.io.message_bus import TCPMessageBus
from tigerbeetle_tpu.state_machine import decode_results, encode_ids
from tigerbeetle_tpu.types import (
    Account,
    AccountFlags,
    CreateAccountResult,
    CreateTransferResult,
    Operation,
    Transfer,
    TransferFlags,
)
from tigerbeetle_tpu.vsr.client import Client, WallTicker

_ACCOUNT_FIELDS = {f.name for f in dataclasses.fields(Account)}
_TRANSFER_FIELDS = {f.name for f in dataclasses.fields(Transfer)}


def _parse_flags(value: str, enum) -> int:
    out = 0
    for name in value.split("|"):
        out |= int(enum[name.strip()])
    return out


def parse_statement(text: str):
    """-> (Operation, events) where events is list[Account|Transfer|int]."""
    text = text.strip().rstrip(";").strip()
    if not text:
        return None, []
    op_name, _, rest = text.partition(" ")
    op = Operation[op_name]
    events = []
    for obj in rest.split(","):
        obj = obj.strip()
        if not obj:
            continue
        kv = {}
        for pair in obj.split():
            key, _, value = pair.partition("=")
            kv[key] = value
        if op == Operation.create_accounts:
            flags = kv.pop("flags", None)
            a = Account(**{k: int(v, 0) for k, v in kv.items()
                           if k in _ACCOUNT_FIELDS})
            if flags:
                a.flags = _parse_flags(flags, AccountFlags)
            events.append(a)
        elif op == Operation.create_transfers:
            flags = kv.pop("flags", None)
            t = Transfer(**{k: int(v, 0) for k, v in kv.items()
                            if k in _TRANSFER_FIELDS})
            if flags:
                t.flags = _parse_flags(flags, TransferFlags)
            events.append(t)
        else:
            events.append(int(kv["id"], 0))
    return op, events


class Repl:
    def __init__(self, addresses, cluster_id: int = 0,
                 client_id: int | None = None):
        self.addresses = addresses
        self.client_id = client_id or random.getrandbits(120) | (1 << 120)
        self.bus = TCPMessageBus(addresses, self.client_id, listen=False)
        # 20ms ticks -> first retry at ~600ms, re-targeted round-robin
        # across the cluster on the runtime's own ladder (an eviction or
        # deadline surfaces as the typed error from take_reply)
        self.client = Client(self.client_id, self.bus, len(addresses),
                             cluster_id, request_timeout_ticks=30,
                             max_backoff_exponent=2)
        self.ticker = WallTicker(self.client, tick_s=0.02)

    # -- request/response over the bus --

    def _await_reply(self, timeout: float = 10.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self.bus.pump(timeout=0.02)
            self.ticker.advance(time.monotonic())
            if self.client.done:
                return self.client.take_reply()
        raise TimeoutError("no reply from cluster")

    def connect(self) -> None:
        self.client.register()
        self._await_reply()
        assert self.client.session != 0

    def execute(self, op: Operation, events) -> str:
        if op == Operation.create_accounts:
            body = types.accounts_to_np(events).tobytes()
        elif op == Operation.create_transfers:
            body = types.transfers_to_np(events).tobytes()
        else:
            body = encode_ids(events)
        self.client.request(op, body)
        _header, reply = self._await_reply()
        return self._render(op, events, reply)

    @staticmethod
    def _render(op: Operation, events, reply: bytes) -> str:
        import numpy as np

        if op in (Operation.create_accounts, Operation.create_transfers):
            sparse = decode_results(reply, op)
            if not sparse:
                return "ok"
            enum = (
                CreateAccountResult
                if op == Operation.create_accounts
                else CreateTransferResult
            )
            return "\n".join(f"[{i}] {enum(c).name}" for i, c in sparse)
        dtype = (
            types.ACCOUNT_DTYPE
            if op == Operation.lookup_accounts
            else types.TRANSFER_DTYPE
        )
        rows = np.frombuffer(reply, dtype=dtype)
        cls = types.Account if op == Operation.lookup_accounts else types.Transfer
        if not len(rows):
            return "(not found)"
        return "\n".join(str(cls.from_np(rows[i])) for i in range(len(rows)))

    # -- the loop --

    def run(self, stream, echo: bool = False) -> int:
        self.connect()
        print(f"connected (session {self.client.session}); "
              "statements end with ';', ctrl-d exits", flush=True)
        buf = ""
        for line in stream:
            if echo:
                print(f"> {line.rstrip()}")
            buf += line
            while ";" in buf:
                stmt, _, buf = buf.partition(";")
                try:
                    op, events = parse_statement(stmt + ";")
                    if op is None:
                        continue
                    print(self.execute(op, events), flush=True)
                except Exception as e:  # noqa: BLE001 — REPL reports, not dies
                    print(f"error: {e}", flush=True)
        return 0
