"""Per-request critical-path latency attribution.

The reference evaluates its pipeline by where a request's time goes
("Blockchain Machine" treats the network path as the accelerator's first
pipeline stage and measures the latency/throughput frontier it feeds);
this module is our decomposition seam: every sampled request is stamped
with monotonic timestamps as it crosses the named pipeline legs

    ingress_admission -> wal_write -> quorum_wait -> fuse_hold ->
    commit_dispatch -> commit_wait -> commit_finalize -> reply_egress

and at reply egress the stamps fold into one `latency.<leg>_us`
histogram per leg plus `latency.e2e_us` (metrics.py CATALOG for units).
Legs are CONSECUTIVE intervals between stamps, so for any single
request sum(legs) == e2e exactly — the decomposition accounts for all
of the time by construction (the bench frontier asserts the accounted
ratio on a live server). Work that runs OFF the critical path is a
parallel LANE, not a leg: the dual-commit device applier's enqueue->
upload lag (`latency.device_apply_lag_us`, models/dual_ledger.py) and
the async WAL write's submit->durable time (`latency.wal_lane_us`,
vsr/journal.py) are observed as their own histograms and never count
into e2e.

SAMPLING: stamping every request would cost ~2.5us of pure Python per
request (9 clock reads + list appends), so the anatomy samples one
request in `sample_every` (default 16; 1 = every request, 0 = off).
Unsampled requests pay only the `want()` countdown plus a handful of
`if token:` guards — the no-op-backend budget test in tests/test_latency
pins the amortized cost under 1us/request. The top-K ring therefore
holds the slowest SAMPLED requests; crank --latency-sample-every 1 when
hunting a specific regression.

DETERMINISM: the replica constructs its anatomy with the Time seam's
monotonic clock (io/time.py), so simulator runs stamp with virtual
ticks and the same seed folds identical histograms — the stamps ride
the deterministic seam, they never inject wall time into a seeded run.
The default clock here exists only for standalone use (budget tests,
ad-hoc instrumentation) and is baselined observability-only.

Records are keyed by the request's cluster-causal trace id
(vsr/header.py trace_id — derived from (client, request checksum), so
the bus can re-derive it from reply-frame bytes at egress with no side
channel). Egress lands in one of two ways: in-process transports finish
the record at the replica's reply send; the TCP bus defers it
(`defer_egress`) and finishes when the flush that carries the reply
frame writes to the socket — the leg then measures finalize -> first
socket write.
"""

from __future__ import annotations

from time import perf_counter_ns  # vet: observability-only default clock

from tigerbeetle_tpu.metrics import NULL_METRICS

# Leg ids (stamp order on the primary's durable path; a leg a request
# never crosses — e.g. fuse_hold with the window off — folds as 0us and
# is dropped from its breakdown record).
LEG_INGRESS = 0  # arrival (gateway admit) -> admission/dedup done
LEG_WAL = 1  # prepare built + WAL write issued (sync path: completed)
LEG_QUORUM = 2  # broadcast -> replication quorum reached
LEG_FUSE = 3  # quorum-ready -> commit dispatch entry (group-fuse hold)
LEG_DISPATCH = 4  # commit dispatch (stage + launch)
LEG_WAIT = 5  # dispatch -> finalize entry (async window / device compute)
LEG_FINALIZE = 6  # finalize (WAL ack wait + drain + reply build)
LEG_EGRESS = 7  # reply built -> reply leaves (bus flush / send)

LEGS = (
    "ingress_admission", "wal_write", "quorum_wait", "fuse_hold",
    "commit_dispatch", "commit_wait", "commit_finalize", "reply_egress",
)

# Parallel-lane histogram names (observed by their owning components —
# dual_ledger's apply loop and the journal's writer pool — never folded
# into a request's critical-path legs).
LANE_DEVICE_APPLY = "latency.device_apply_lag_us"
LANE_WAL = "latency.wal_lane_us"

# A gateway arrival stamp older than this is stale evidence (the frame
# it timed was dropped before the replica opened a record — a dup, a
# shed, a non-primary pass-through) and must not inflate the NEXT
# sampled request's ingress_admission leg.
_ARRIVAL_STALE_NS = 100_000_000


class LatencyAnatomy:
    """Per-request stamp collector + per-leg histogram folder + top-K
    slowest ring. One per replica; the gateway and bus hold references.

    Protocol (the replica drives it):
      if anatomy.want():                  # sampling countdown
          tok = anatomy.open(trace_id)    # begin the record
      ...
      if tok: anatomy.stamp(tok, LEG_X)   # consecutive leg boundaries
      ...
      anatomy.egress(tok, client, ctx)    # finish (or hand to the bus)
    """

    def __init__(self, metrics=None, clock=None, sample_every: int = 16,
                 capacity: int = 512, top_k: int = 32):
        m = metrics if metrics is not None else NULL_METRICS
        self.metrics = m
        self._clock = clock if clock is not None else perf_counter_ns
        self.sample_every = sample_every
        self.capacity = capacity
        self.top_k = top_k
        # leg-indexed histogram handles, bound once (a registry lookup
        # per stamp would dwarf the stamp)
        self._h = [m.histogram(f"latency.{leg}_us") for leg in LEGS]
        self._h_e2e = m.histogram("latency.e2e_us")
        self._c_samples = m.counter("latency.samples")
        self._c_dropped = m.counter("latency.dropped")
        # open records: trace id -> [t0, leg, t1, leg, t2, ...]
        self._recs: dict[int, list] = {}
        # deferred-egress handoff to the TCP bus: (client, context) ->
        # token; the bus pops the match when the reply frame is queued
        # and finishes the record at the flush that writes it
        self.defer_egress = False
        self.pending_egress: dict[tuple, int] = {}
        # sampling state: _take flags the NEXT request as sampled; the
        # countdown advances in want() on the unsampled path
        self._take = sample_every > 0
        self._since = 0
        self._arrival = 0  # gateway arrival stamp for the sampled-next req
        # top-K slowest sampled requests, ascending by e2e; _slow_min is
        # the current cutoff so the common case is ONE compare
        self._slow: list[tuple[int, dict]] = []
        self._slow_min = -1

    # -- the hot path ---------------------------------------------------

    def arrive(self) -> None:
        """Gateway admission stamp (ingress/gateway.py): records the
        arrival time IF the next request is the sampled one — one attr
        test per admitted frame otherwise."""
        if self._take:
            self._arrival = self._clock()

    def want(self) -> bool:
        """Advance the sampling countdown; True when the caller should
        open() a record for this request. The unsampled path is this one
        call: a compare or two, an increment, done. sample_every <= 0
        disables outright — checked first, because the knob can be
        turned off at runtime while `_take` is still armed from
        construction."""
        if self.sample_every <= 0:
            return False
        if self._take:
            return True
        self._since += 1
        if self._since + 1 >= self.sample_every:
            self._since = 0
            self._take = True
        return False

    def open(self, tid: int) -> int:
        """Begin the sampled record for trace id `tid`; returns the
        token (the tid) the caller guards later stamps with, or 0 when
        the record cannot open (duplicate id, sampling raced off)."""
        if not self._take:
            return 0
        self._take = self.sample_every <= 1
        now = self._clock()
        a = self._arrival
        self._arrival = 0
        t0 = a if (a and now - a < _ARRIVAL_STALE_NS) else now
        recs = self._recs
        if tid in recs:
            return 0
        if len(recs) >= self.capacity:
            # evict the oldest open record (its reply was shed/lost)
            recs.pop(next(iter(recs)))
            self._c_dropped.add()
        recs[tid] = [t0, LEG_INGRESS, now]
        return tid

    def stamp(self, tok: int, leg: int) -> None:
        r = self._recs.get(tok)
        if r is not None:
            r.append(leg)
            r.append(self._clock())

    def egress(self, tok: int, client: int, context: int) -> None:
        """Close the record at reply egress. With `defer_egress` (TCP
        bus installed) the record is parked for the bus, keyed by the
        reply frame's (client, context) pair; otherwise it finishes
        now (in-process transports deliver synchronously)."""
        if self.defer_egress:
            pe = self.pending_egress
            if len(pe) >= 128:  # replies that never flushed (conn died)
                self.discard(pe.pop(next(iter(pe))))
            pe[(client, context)] = tok
        else:
            self.finish(tok)

    def finish(self, tok: int) -> None:
        """Final stamp (reply_egress) + fold into the histograms and the
        top-K ring. Idempotent: a second finish for the same token is a
        dict miss."""
        r = self._recs.pop(tok, None)
        if r is None:
            return
        r.append(LEG_EGRESS)
        r.append(self._clock())
        t0 = r[0]
        e2e = r[-1] - t0
        hs = self._h
        prev = t0
        for i in range(1, len(r), 2):
            t = r[i + 1]
            hs[r[i]].observe((t - prev) / 1000.0)
            prev = t
        self._h_e2e.observe(e2e / 1000.0)
        self._c_samples.add()
        if e2e > self._slow_min or len(self._slow) < self.top_k:
            self._slow_insert(tok, t0, e2e, r)

    # -- cold paths -----------------------------------------------------

    def discard(self, tok) -> None:
        """Drop an open record without folding (view change abandoned
        the op; capacity eviction)."""
        if tok is not None:
            self._recs.pop(tok, None)

    def _slow_insert(self, tok: int, t0: int, e2e: int, r: list) -> None:
        legs: dict[str, float] = {}
        prev = t0
        for i in range(1, len(r), 2):
            t = r[i + 1]
            d = (t - prev) / 1000.0
            prev = t
            if d or r[i] == LEG_EGRESS:
                name = LEGS[r[i]]
                legs[name] = round(legs.get(name, 0.0) + d, 3)
        rec = {
            "trace": f"{tok:016x}",
            "t0_ns": t0,
            "e2e_us": round(e2e / 1000.0, 3),
            "legs": legs,
            "dominant": max(legs, key=legs.get) if legs else None,
        }
        slow = self._slow
        slow.append((e2e, rec))
        slow.sort(key=lambda x: x[0])
        if len(slow) > self.top_k:
            slow.pop(0)
        self._slow_min = slow[0][0]

    def slowest(self, limit: int = 0) -> list[dict]:
        """The slowest sampled requests, worst first (the SIGQUIT dump,
        the [stats] wire snapshot and `tigerbeetle inspect live` all
        read this)."""
        out = [rec for _e2e, rec in reversed(self._slow)]
        return out[:limit] if limit else out


# -- device applier anatomy (models/dual_ledger.py apply loop) ---------
#
# The replica-side anatomy above names `commit_wait` as one leg; the
# device anatomy decomposes the applier's copy of that window into
# CONSECUTIVE sub-legs, so for a sampled item sum(sub-legs) == the
# enqueue -> finalize-visible span exactly — accounted_ratio is 1.0 at
# device granularity by construction. All stamps after open() land on
# the apply thread; the enqueue stamp travels in the 8-slot apply tuple
# (slot 7, `lat_ns`) from the commit path, same perf_counter domain.

DLEG_QUEUE = 0  # apply_commit enqueue -> apply-loop dequeue
DLEG_COALESCE = 1  # dequeue -> this item's stretch enters staging
DLEG_H2D = 2  # staging entry -> h2d upload issued (group path)
DLEG_DISPATCH = 3  # upload issued -> kernel dispatch call returned
DLEG_BUSY = 4  # dispatch -> fold digest fence ready (device compute)
DLEG_FINALIZE = 5  # fence ready -> applied counters/parity visible

DEVICE_LEGS = (
    "queue_wait", "coalesce_hold", "h2d_stage",
    "dispatch", "device_busy", "finalize_visible",
)


class DeviceAnatomy:
    """Per-apply-item stamp collector for the dual-commit device
    applier: folds consecutive sub-leg intervals into the `device.*`
    histogram family plus a top-K slowest ring naming the dominant
    sub-leg. One per DualLedger; driven ONLY by the apply thread
    (open/stamp/finish), so no locking — the enqueue timestamp arrives
    by value inside the apply tuple.  # vet: owner=device-shadow
    """

    def __init__(self, metrics=None, clock=None, top_k: int = 32,
                 capacity: int = 512):
        m = metrics if metrics is not None else NULL_METRICS
        self.metrics = m
        self._clock = clock if clock is not None else perf_counter_ns
        self.top_k = top_k
        self.capacity = capacity
        self._h = [m.histogram(f"device.{leg}_us") for leg in DEVICE_LEGS]
        self._h_e2e = m.histogram("device.apply_e2e_us")
        self._c_samples = m.counter("device.samples")
        # open records: trace id -> [t_enq, leg, t, leg, t, ...]
        self._recs: dict[int, list] = {}
        self._slow: list[tuple[int, dict]] = []
        self._slow_min = -1

    def open(self, tid: int, t_enq: int, t_deq: int = 0) -> int:
        """Begin a record for a sampled apply item: `tid` is any
        nonzero per-item key (the cluster trace id when one flows, the
        op number otherwise), `t_enq` the commit path's enqueue stamp
        (apply tuple slot 7), `t_deq` the dequeue time (defaults to
        now) — together they close the queue_wait sub-leg immediately.
        Returns the token (the tid) or 0 when the record cannot open
        (zero/duplicate id)."""
        recs = self._recs
        if not tid or tid in recs:
            return 0
        if len(recs) >= self.capacity:
            recs.pop(next(iter(recs)))
        recs[tid] = [t_enq, DLEG_QUEUE, t_deq or self._clock()]
        return tid

    def stamp(self, tok: int, leg: int, t: int = 0) -> None:
        r = self._recs.get(tok)
        if r is not None:
            r.append(leg)
            r.append(t or self._clock())

    def finish(self, tok: int, t: int = 0) -> None:
        """Final stamp (finalize_visible) + fold. Idempotent."""
        r = self._recs.pop(tok, None)
        if r is None:
            return
        r.append(DLEG_FINALIZE)
        r.append(t or self._clock())
        t0 = r[0]
        e2e = r[-1] - t0
        hs = self._h
        prev = t0
        for i in range(1, len(r), 2):
            ti = r[i + 1]
            hs[r[i]].observe((ti - prev) / 1000.0)
            prev = ti
        self._h_e2e.observe(e2e / 1000.0)
        self._c_samples.add()
        if e2e > self._slow_min or len(self._slow) < self.top_k:
            self._slow_insert(tok, t0, e2e, r)

    def discard(self, tok) -> None:
        if tok:
            self._recs.pop(tok, None)

    def _slow_insert(self, tok: int, t0: int, e2e: int, r: list) -> None:
        legs: dict[str, float] = {}
        prev = t0
        for i in range(1, len(r), 2):
            t = r[i + 1]
            d = (t - prev) / 1000.0
            prev = t
            if d or r[i] == DLEG_FINALIZE:
                name = DEVICE_LEGS[r[i]]
                legs[name] = round(legs.get(name, 0.0) + d, 3)
        rec = {
            "trace": f"{tok:016x}",
            "t0_ns": t0,
            "e2e_us": round(e2e / 1000.0, 3),
            "legs": legs,
            "dominant": max(legs, key=legs.get) if legs else None,
        }
        slow = self._slow
        slow.append((e2e, rec))
        slow.sort(key=lambda x: x[0])
        if len(slow) > self.top_k:
            slow.pop(0)
        self._slow_min = slow[0][0]

    def slowest(self, limit: int = 0) -> list[dict]:
        """Slowest sampled apply items, worst first (the SIGQUIT dump,
        [stats] wire snapshot and `inspect live` read this)."""
        out = [rec for _e2e, rec in reversed(self._slow)]
        return out[:limit] if limit else out


class _NullDeviceAnatomy(DeviceAnatomy):
    def __init__(self):
        super().__init__(metrics=NULL_METRICS)

    def open(self, tid, t_enq, t_deq=0):
        return 0


NULL_DEVICE_ANATOMY = _NullDeviceAnatomy()


def device_leg_totals(metrics_snapshot: dict) -> dict[str, dict]:
    """Per-device-sub-leg {count, total_us} from a registry snapshot —
    same shape as leg_totals(), feeding the same dominant_leg() delta
    math for the frontier's per-step sub-leg attribution."""
    hists = metrics_snapshot.get("histograms", {})
    out = {}
    for leg in DEVICE_LEGS:
        h = hists.get(f"device.{leg}_us")
        if h and h.get("count"):
            out[leg] = {
                "count": h["count"],
                "total_us": h["count"] * h.get("mean", 0.0),
            }
    return out


class _NullAnatomy(LatencyAnatomy):
    """Stamping disabled entirely (sample_every=0 shares the same fast
    path; this exists for callers that want a shared inert instance)."""

    def __init__(self):
        super().__init__(metrics=NULL_METRICS, sample_every=0)


NULL_ANATOMY = _NullAnatomy()


def leg_totals(metrics_snapshot: dict) -> dict[str, dict]:
    """Per-leg {count, total_us} extracted from a registry snapshot's
    histogram section (count and mean are what snapshot() exposes; the
    product reconstructs the total). Shared by the bench frontier's
    dominant-leg delta math and `inspect live --watch`."""
    hists = metrics_snapshot.get("histograms", {})
    out = {}
    for leg in LEGS:
        h = hists.get(f"latency.{leg}_us")
        if h and h.get("count"):
            out[leg] = {
                "count": h["count"],
                "total_us": h["count"] * h.get("mean", 0.0),
            }
    return out


def windowed_leg_totals(entries: list[dict], legs=LEGS,
                        prefix: str = "latency") -> dict[str, dict]:
    """Per-leg {count, total_us} summed over flight-recorder entries'
    WINDOWED histograms — the per-PHASE analog of leg_totals(): a
    cumulative snapshot delta needs live before/after probes, but a
    recorder slice already carries each interval's window, so a phase's
    leg totals are just the sum of its entries' windows. Shared by the
    prodday scorecard (live history via [stats], sim-twin recorder
    directly). Pass legs=DEVICE_LEGS, prefix="device" for the
    commit_wait sub-leg decomposition."""
    out: dict[str, dict] = {}
    for e in entries:
        hists = e.get("histograms", {})
        for leg in legs:
            w = hists.get(f"{prefix}.{leg}_us")
            if w and w.get("count"):
                d = out.setdefault(leg, {"count": 0, "total_us": 0.0})
                d["count"] += w["count"]
                d["total_us"] += w["count"] * w.get("mean", 0.0)
    for d in out.values():
        d["total_us"] = round(d["total_us"], 3)
    return out


def dominant_in_entries(entries: list[dict], legs=LEGS,
                        prefix: str = "latency") -> tuple[str | None, float]:
    """(leg, share) with the largest windowed total across a recorder
    slice — the prodday scorecard's "why did this phase blow its
    budget" attribution (dominant_leg()'s shape, fed from windows
    instead of snapshot deltas). Ties break by leg name for
    deterministic scorecards."""
    totals = windowed_leg_totals(entries, legs, prefix)
    if not totals:
        return None, 0.0
    grand = sum(d["total_us"] for d in totals.values())
    leg = max(sorted(totals), key=lambda k: totals[k]["total_us"])
    share = totals[leg]["total_us"] / grand if grand else 0.0
    return leg, round(share, 4)


def dominant_leg(before: dict, after: dict) -> tuple[str | None, float]:
    """(leg, share) with the largest total-time delta between two
    leg_totals() extracts — the frontier's per-step attribution."""
    deltas = {}
    for leg, a in after.items():
        b = before.get(leg, {"total_us": 0.0})
        d = a["total_us"] - b["total_us"]
        if d > 0:
            deltas[leg] = d
    if not deltas:
        return None, 0.0
    total = sum(deltas.values())
    leg = max(deltas, key=deltas.get)
    return leg, round(deltas[leg] / total, 4) if total else 0.0
