"""The process CLI: format | start | version | client | repl.

The reference's surface (reference: src/tigerbeetle/main.zig:26-33
composition root, src/tigerbeetle/cli.zig:54-116 flags):

  python -m tigerbeetle_tpu format --cluster=0 --replica=0 \
      --replica-count=1 data.tigerbeetle
  python -m tigerbeetle_tpu start --addresses=127.0.0.1:3001 [--aof=f] \
      data.tigerbeetle
  python -m tigerbeetle_tpu version
  python -m tigerbeetle_tpu repl --addresses=...

One dataclass per command is the whole CLI surface (the reference derives
its CLI from structs the same way, src/flags.zig); `flags.parse`
introspects it. `start` is the composition root: FileStorage +
TCPMessageBus + RealTime injected into the Replica, then the event loop
(bus pump + replica ticks at tick_ms; reference: main.zig start loop).
"""

from __future__ import annotations

import dataclasses
import sys
import time

from tigerbeetle_tpu import flags
from tigerbeetle_tpu.flags import positional

VERSION = "0.3.0"


@dataclasses.dataclass
class FormatArgs:
    file: str = positional("data file path")
    cluster: int = 0
    replica: int = 0
    replica_count: int = 1
    grid_mb: int = 64
    # Session capacity (consensus-affecting: part of the config
    # fingerprint, so format and start must agree). clients_max is the
    # replicated client-table cap; client_reply_slots caps the DURABLE
    # reply slots separately (each costs message_size_max on disk —
    # 10k+ multiplexed sessions cannot each own one; 0 = one per
    # client, the pre-ingress layout).
    clients_max: int = 32
    client_reply_slots: int = 0


@dataclasses.dataclass
class StartArgs:
    addresses: str  # comma-separated host:port per replica
    file: str = positional("data file path")
    replica: int = 0
    grid_mb: int = 64
    account_slots_log2: int = 20
    transfer_slots_log2: int = 24
    aof: str = ""  # append-only disaster-recovery log path
    statsd: str = ""  # statsd host | :port | host:port (batched emission)
    # Change-data-capture (tigerbeetle_tpu/cdc): attach a live CdcPump
    # tailing this replica's committed ops into a JSONL file and/or UDP
    # datagrams. The pump rides the event loop with a bounded per-turn
    # budget and pauses (never the replica) when the sink refuses.
    cdc_jsonl: str = ""  # change-stream JSONL path
    cdc_udp: str = ""  # change-stream UDP host | :port | host:port
    cdc_cursor: str = ""  # cursor file (default: <cdc-jsonl>.cursor)
    cdc_window: int = 256  # live in-flight window (ops)
    # Ops between durable-cursor acks. Every ack flushes the sink first,
    # so this is ALSO the staleness bound an external tailer of the JSONL
    # file sees; the live federation agent runs 1 (flush per op).
    cdc_ack_interval: int = 32
    # Deliberately slow consumer model (bench A/B): the sink accepts at
    # most one op's records per this many microseconds, REFUSING (not
    # sleeping) in between — backpressure without blocking the loop.
    cdc_slow_us: int = 0
    # Count-throttled slow consumer (the prodday timeline's laggard;
    # live analog of the simulator's _FanoutStore throttle_every): the
    # LAST named sink accepts only every K-th emission attempt. Under
    # --cdc-fanout only that consumer lags (its fanout position falls
    # behind; ingress.fanout_lag_ops names the gap). 0 disables.
    cdc_slow_every: int = 0
    # dump a Chrome trace-event JSON (Perfetto-loadable) of the commit
    # pipeline's spans to this path on shutdown (SIGTERM)
    trace: str = ""
    commit_window: int = 16  # async commits in flight (0 = sync); a full
    # GROUP_MAX fused group stays un-drained while the next one arrives
    # Group-commit fuse window in MICROSECONDS (0 disables): a short
    # quorum-ready run of create_transfers holds this long — only while
    # earlier commits are in flight — so near-simultaneous arrivals
    # coalesce into one fused dispatch (vsr/replica.py fuse_window_ns).
    # -1 (the default) AUTOTUNES: AIMD from observed hold outcomes —
    # expired-short holds widen the window, holds that fill to GROUP_MAX
    # shrink it (bounded 500us..8ms; starts at 2000us). The r05 driver's
    # 0.46 hit rate against the CPU A/B's 0.85 motivated making the
    # window track the workload instead of trusting one constant.
    fuse_window_us: int = -1
    # Commit backend: "native" = the C++ host engine (native/ledger.cc —
    # the durable hot path; this environment's tunneled TPU degrades
    # permanently on any device->host fetch, see models/native_ledger.py),
    # "native+device" = the SHADOW dual mode: native serves replies while
    # the device mirrors every prepare (h2d only) and shutdown verifies
    # the device state bit-exact (models/dual_ledger.py),
    # "dual" = the dual-commit FOLLOWER plan: like native+device, but the
    # REPLICA enqueues committed ops to the device applier at commit
    # finalize — rolling per-op hash-log rings (first divergent op named
    # exactly), bounded-lag admission backpressure, checkpoint/state-sync
    # drains, and restart recovery via snapshot row install,
    # "device" = the JAX DeviceLedger (the TPU compute path; supports
    # HBM->LSM spill), "sharded" = the multi-chip ShardedLedger over a
    # jax.sharding.Mesh (parallel/mesh.py; slots flags are PER SHARD).
    backend: str = "native"
    # Dual-commit follower: device-applier lag (committed ops not yet
    # dispatched to the device) beyond this window throttles admission
    # (Replica.ingress_occupancy / the _on_request cap) instead of
    # growing without bound.
    device_lag_window: int = 128
    # hash_log surface (testing/hash_log.py; reference -Dhash-log-mode,
    # src/testing/hash_log.zig): "record:<path>" streams one prepare/reply
    # checksum pair per committed op to <path> at shutdown; "check:<path>"
    # replays against a recording and fails AT the first divergent op.
    # A bare "<path>" records.
    hash_log: str = ""
    shards: int = 0  # sharded backend: devices in the mesh (0 = all)
    # Session capacity — MUST match the values the data file was
    # formatted with (config fingerprint; see FormatArgs).
    clients_max: int = 32
    client_reply_slots: int = 0
    # Ingress gateway (tigerbeetle_tpu/ingress): session-multiplexed
    # admission front door. --ingress installs the gateway (credit-based
    # admission fed by pipeline occupancy + pool budget; saturated
    # requests get a typed busy reply instead of queueing or dropping).
    ingress: bool = False
    ingress_sessions_max: int = 0  # gateway session-table cap (0 = uncapped)
    ingress_backlog: int = 1024  # TCP listen backlog (accept-drain loop)
    ingress_accept_budget: int = 256  # accepts drained per readiness event
    ingress_dispatch_budget: int = 256  # frames per connection per pump turn
    # CDC fan-out: with BOTH --cdc-jsonl and --cdc-udp, give each sink
    # its own consumer (cursor + position) over one shared tail — a slow
    # sink pauses only itself (ingress/fanout.py). Default keeps the
    # PR-4 behavior: one pump, one cursor, all sinks move together.
    cdc_fanout: bool = False
    # Per-request critical-path attribution (tigerbeetle_tpu/latency.py):
    # one request in N is stamped at every pipeline leg and folded into
    # the latency.* histograms at reply egress; the slowest sampled
    # requests keep full breakdowns (SIGQUIT dump + `inspect live`).
    # 1 = every request (regression hunting), 0 = off.
    latency_sample_every: int = 16
    # Flight recorder (metrics.py FlightRecorder): seconds between
    # time-series snapshots of the registry (counter deltas + windowed
    # histogram percentiles), ring of ~180 entries served through the
    # [stats] wire command (`inspect live --watch`). 0 disables.
    flight_interval_s: float = 1.0
    # XLA trace bridge (dual/native+device backends): capture a bounded
    # jax.profiler window on the device-applier thread into this
    # directory, starting at the applier's first dequeue after serving
    # begins. scripts/stitch_trace.py --device-trace merges the captured
    # device timeline into the stitched Perfetto file, clock-aligned to
    # our spans (the directory also gets device_trace_meta.json).
    device_trace: str = ""
    device_trace_s: float = 3.0  # window length (seconds)
    # Checkpoint state commitments (federation/commitment.py): fold the
    # ledger's state fingerprint into a hash chain at every op multiple
    # of this interval. The chain rides checkpoints (restart-stable),
    # the [stats] snapshot, `inspect commitments`, and — when a CDC sink
    # is attached — the change stream itself as `commitment` records an
    # external consumer verifies with `inspect commitments --stream`.
    # 0 disables.
    commitment_interval: int = 0
    # Cross-ledger federation identity (federation/topology.py): which
    # region of an N-region federation this cluster is. Purely
    # declarative on the server (settlement runs in the agent process —
    # scripts/federate.py), but stamped into the [stats] snapshot so
    # operators and the live harness can tell regions apart.
    federation_region: int = -1
    federation_regions: int = 0


@dataclasses.dataclass
class ReplArgs:
    addresses: str
    cluster: int = 0


@dataclasses.dataclass
class InspectArgs:
    """Offline data-file + live-state introspection (tigerbeetle_tpu/
    inspect.py; reference: src/tigerbeetle/inspect.zig). Topics:
    superblock | wal | replies | grid | lsm | client-table | all decode
    the data file; live reads the [stats] registry snapshot off a
    running server (--addresses)."""

    topic: str = positional(
        "superblock | wal | replies | grid | lsm | client-table | all | "
        "live | commitments"
    )
    file: str = dataclasses.field(
        default="", metadata={"positional": True,
                              "help": "data file path (offline topics)"}
    )
    op: int = -1  # wal: dump ONE prepare (inspect wal --op N)
    slot: int = -1  # wal: restrict the scan to one slot
    addresses: str = ""  # live: host:port of the running replica
    json: bool = False  # machine-readable report
    # live repeated-snapshot mode: re-poll every N seconds and print
    # per-interval deltas/rates from the server's flight-recorder
    # history (works against wedged replicas like single-shot live)
    watch: float = 0.0
    watch_count: int = 0  # stop after N polls (0 = until interrupted)
    # geometry the file was formatted with (same contract as `start`:
    # only non-defaults need repeating; the grid size is inferred from
    # the file size)
    clients_max: int = 32
    client_reply_slots: int = 0
    forest_blocks: int = 0  # LSM forest geometry (spill-enabled files)
    # `commitments` topic, verify mode: replay this CDC stream JSONL
    # through a fresh oracle and re-derive the commitment chain — a
    # tampered stream/state fails naming the exact checkpoint. With
    # --addresses instead, reads the live chain off the [stats] wire;
    # with a data file, decodes the checkpointed chain offline.
    stream: str = ""


@dataclasses.dataclass
class ChaosArgs:
    """Live-cluster chaos run (testing/chaos.py): spawn a real N-replica
    TCP cluster + a multiplexed client fleet on the fault-tolerant
    client runtime, inject live faults (SIGKILL/restart, SIGSTOP gray
    failure, connection resets, a WAL disk-fault flip on restart), and
    verify zero lost / zero duplicated transfers (client replies vs CDC
    stream vs wire conservation, dual-mode hash-log parity), reporting
    time-to-first-commit-after-kill."""

    sessions: int = 64
    conns: int = 4
    accounts: int = 128
    events_per_batch: int = 16
    batches_per_session: int = 6
    replicas: int = 3
    backend: str = "native"
    faults: str = "kill_primary"  # comma list, see CHAOS_ACTIONS
    restart_after_s: float = 2.0
    gray_s: float = 3.0
    disk_fault: bool = True  # flip WAL bytes on the first restart
    ingress: bool = False  # front every replica with the gateway
    seed: int = 1
    deadline_s: float = 600.0
    json: str = ""  # write the full report here too
    # Region-level federation mode (federation/live.py): spawn
    # --federation-regions whole clusters, run the live settlement agent
    # between them, SIGKILL EVERY replica of one region mid-settlement,
    # restart it from disk, and verify cross-region conservation plus
    # each region's commitment stream against its published head. The
    # per-session workload knobs above don't apply; `payments` origin
    # pendings are issued per region.
    kill_cluster: bool = False
    federation_regions: int = 2
    payments: int = 24
    commitment_interval: int = 8


@dataclasses.dataclass
class CdcArgs:
    """Offline change-stream tool: replay an AOF into a sink, resuming
    from (and advancing) a durable consumer cursor. The disaster-recovery
    log is the complete committed history from op 1; result codes are
    regenerated exactly by replaying each prepare through the scalar
    oracle (parity-locked with the device engines)."""

    file: str = positional("append-only file (AOF) path")
    consumer: str = "default"  # cursor namespace
    cursor: str = ""  # cursor file (default: <aof>.<consumer>.cursor)
    sink: str = "stdout"  # stdout | jsonl:<path> | udp:host[:port]
    limit: int = 0  # stop after N ops (0 = to end of log)


def _parse_addresses(s: str) -> list[tuple[str, int]]:
    out = []
    for part in s.split(","):
        host, _, port = part.strip().rpartition(":")
        out.append((host or "127.0.0.1", int(port)))
    return out


def _storage(path: str, cluster_cfg, create: bool, grid_mb: int):
    from tigerbeetle_tpu.io.storage import FileStorage, ZoneLayout

    layout = ZoneLayout(cluster_cfg, grid_size=grid_mb * 1024 * 1024)
    return FileStorage(path, layout, create=create)


def cmd_format(args) -> int:
    from tigerbeetle_tpu.constants import ConfigCluster
    from tigerbeetle_tpu.vsr.durable import format_data_file

    cluster_cfg = ConfigCluster(
        replica_count=args.replica_count,
        clients_max=args.clients_max,
        client_reply_slots=args.client_reply_slots,
    )
    storage = _storage(args.file, cluster_cfg, create=True, grid_mb=args.grid_mb)
    format_data_file(
        storage, cluster_cfg, cluster_id=args.cluster, replica=args.replica
    )
    storage.close()
    print(f"formatted {args.file}: cluster={args.cluster} "
          f"replica={args.replica}/{args.replica_count}")
    return 0


class _FanoutSink:
    """start --cdc-jsonl + --cdc-udp together: EVERY sink is offered each
    emission (no short-circuit), and the op counts as delivered only when
    all accepted. A refusal by one member means the pump retries the op,
    so sinks that already accepted see it again — at-least-once per sink,
    dedupable by op like any other redelivery. (Both current members
    always accept; this matters only for future refusing sinks.)"""

    def __init__(self, sinks):
        self.sinks = sinks

    def emit_lines(self, lines) -> bool:
        results = [s.emit_lines(lines) for s in self.sinks]
        return all(results)

    def flush(self) -> None:
        for s in self.sinks:
            s.flush()

    def close(self) -> None:
        for s in self.sinks:
            s.close()


def _install_parent_death_watchdog() -> None:
    """Die with the spawner — OPT-IN via TB_PARENT_WATCHDOG=1 (the bench and
    test harnesses set it when they spawn `start` as a subprocess). If the
    harness is SIGKILLed (or a teardown path is skipped) the server used to
    outlive it and burn CPU on the shared bench machine, skewing every
    later measurement. PR_SET_PDEATHSIG delivers SIGTERM the moment the
    parent thread exits; the ppid re-check closes the race where the parent
    died before the prctl landed. Opt-in because a production/daemonized
    start (systemd, `... start &` from a wrapper that exits) legitimately
    outlives its launcher."""
    import ctypes
    import os
    import signal

    if os.environ.get("TB_PARENT_WATCHDOG") != "1":
        return
    if not sys.platform.startswith("linux"):
        return
    try:
        libc = ctypes.CDLL(None, use_errno=True)
        PR_SET_PDEATHSIG = 1
        libc.prctl(PR_SET_PDEATHSIG, signal.SIGTERM, 0, 0, 0)
        if os.getppid() == 1:  # parent already gone: orphaned at birth
            raise SystemExit(0)
    except (OSError, AttributeError):
        pass  # non-glibc platform: watchdog unavailable, teardown still kills


def cmd_start(args) -> int:
    import faulthandler
    import os
    import signal

    faulthandler.register(signal.SIGUSR1)  # kill -USR1 <pid> dumps stacks
    _install_parent_death_watchdog()
    debug_boot = bool(os.environ.get("TB_DEBUG"))

    def boot(msg: str) -> None:
        if debug_boot:
            print(f"[boot] {msg}", file=sys.stderr, flush=True)

    plat = os.environ.get("TB_JAX_PLATFORM")
    if plat:  # tests pin the CPU backend for spawned servers
        import jax

        jax.config.update("jax_platforms", plat)

    from tigerbeetle_tpu.aof import AOF
    from tigerbeetle_tpu.constants import ConfigCluster, ConfigProcess
    from tigerbeetle_tpu.io.message_bus import TCPMessageBus
    from tigerbeetle_tpu.io.time import RealTime
    from tigerbeetle_tpu.metrics import Metrics
    from tigerbeetle_tpu.statsd import StatsD, StatsDEmitter, parse_addr
    from tigerbeetle_tpu.tracer import JsonTracer, Tracer
    from tigerbeetle_tpu.vsr.replica import Replica

    # ONE registry + tracer for the whole process: the replica, bus,
    # journal, ledger and spill pipeline all report here, and the [stats]
    # line / --statsd emission / --trace dump read from it (the reference
    # wires tracer.zig + statsd.zig through the same stages).
    metrics = Metrics()
    tracer = JsonTracer(metrics=metrics) if args.trace else Tracer()

    addresses = _parse_addresses(args.addresses)
    cluster_cfg = ConfigCluster(
        replica_count=len(addresses),
        clients_max=args.clients_max,
        client_reply_slots=args.client_reply_slots,
    )
    process_cfg = ConfigProcess(
        account_slots_log2=args.account_slots_log2,
        transfer_slots_log2=args.transfer_slots_log2,
    )
    boot("imports done")
    storage = _storage(args.file, cluster_cfg, create=False, grid_mb=args.grid_mb)
    boot("storage open")
    bus = TCPMessageBus(
        addresses, args.replica, listen=True,
        listen_backlog=args.ingress_backlog,
        accept_budget=args.ingress_accept_budget,
        dispatch_budget=args.ingress_dispatch_budget,
    )
    bus.metrics = metrics
    bus.tracer = tracer
    boot("bus bound")  # must not contain "listening": spawners match on it
    backend_factory = None
    if args.backend == "native":
        from tigerbeetle_tpu.models.native_ledger import NativeLedger

        backend_factory = lambda: NativeLedger(  # noqa: E731
            args.account_slots_log2, args.transfer_slots_log2
        )
    elif args.backend in ("native+device", "dual"):
        from tigerbeetle_tpu.models.dual_ledger import DualLedger

        backend_factory = lambda: DualLedger(  # noqa: E731
            args.account_slots_log2, args.transfer_slots_log2,
            # compiles happen at boot, before "listening" — an in-window
            # compile stalls the apply queue into the reply path
            warm_kernels=True,
            # "dual" = the follower plan: the replica enqueues committed
            # ops at finalize, with hash-log rings + lag backpressure
            follower=args.backend == "dual",
            lag_window=args.device_lag_window,
        )
    elif args.backend == "sharded":
        import jax
        import numpy as _np
        from jax.sharding import Mesh

        from tigerbeetle_tpu.parallel.mesh import ShardedLedger

        devs = jax.devices()
        if args.shards:
            if args.shards > len(devs):
                flags.fatal(
                    f"--shards {args.shards} but only {len(devs)} device(s) "
                    "available — a silently smaller mesh would write "
                    "checkpoints with the wrong shard geometry"
                )
            devs = devs[: args.shards]
        mesh = Mesh(_np.array(devs), ("shard",))
        backend_factory = lambda: ShardedLedger(  # noqa: E731
            mesh, process_cfg
        )
    elif args.backend != "device":
        flags.fatal(
            f"unknown --backend {args.backend!r} "
            "(native|native+device|dual|device|sharded)"
        )
    replica = Replica(
        args.replica, len(addresses), storage, bus, RealTime(),
        cluster_cfg, process_cfg, backend_factory=backend_factory,
        # production server, real time: spill/grid IO on a worker thread
        # (deterministic harnesses keep the default "deferred" executor)
        spill_io="threaded",
        metrics=metrics,
        tracer=tracer,
    )
    boot("replica constructed (device state allocated)")
    # latency anatomy: sampling knob + TCP egress (the bus finishes a
    # sampled record at the flush that writes its reply frame, so the
    # reply_egress leg measures finalize -> first socket write)
    replica.latency.sample_every = args.latency_sample_every
    replica.latency.defer_egress = True
    bus.latency = replica.latency
    flight = None
    if args.flight_interval_s > 0:
        from tigerbeetle_tpu.metrics import FlightRecorder

        flight = FlightRecorder(metrics)
        replica.flight_recorder = flight  # [stats] wire command history
    if args.aof:
        replica.aof = AOF(args.aof)
    replica.commit_window = args.commit_window
    if args.fuse_window_us < 0:
        # autotune (the default): start at the old 2ms constant, adapt
        # from hold outcomes (vsr/replica.py _fuse_hold AIMD)
        replica.fuse_autotune = True
        replica.fuse_window_ns = 2_000_000
    else:
        replica.fuse_window_ns = args.fuse_window_us * 1000
    if args.commitment_interval > 0:
        from tigerbeetle_tpu.federation.commitment import CommitmentLog

        # install BEFORE open(): the chain restores from the checkpoint
        # meta, then WAL replay re-records the tail idempotently
        replica.commitment_log = CommitmentLog(args.commitment_interval)
    hash_log = None
    if args.hash_log:
        from tigerbeetle_tpu.testing.hash_log import HashLog, parse_hash_log_spec

        mode, hl_path = parse_hash_log_spec(args.hash_log)
        hash_log = HashLog(mode, path=hl_path)
        # attach BEFORE open(): single-replica recovery re-commits the
        # journal tail — record mode re-records identical entries, check
        # mode re-verifies them (both idempotent by op)
        hash_log.attach(replica)
    cdc_pump = None
    if args.cdc_jsonl or args.cdc_udp:
        from tigerbeetle_tpu.cdc import (
            CdcPump,
            CountThrottleSink,
            FileCursor,
            JsonlFileSink,
            ThrottleSink,
            UdpSink,
        )

        named = []  # (consumer name, sink)
        if args.cdc_jsonl:
            named.append(("jsonl", JsonlFileSink(args.cdc_jsonl)))
        if args.cdc_udp:
            named.append(("udp", UdpSink(*parse_addr(args.cdc_udp))))
        if args.cdc_slow_us:
            named = [
                (n, ThrottleSink(s, args.cdc_slow_us)) for n, s in named
            ]
        if args.cdc_slow_every:
            # one count-throttled laggard: only the LAST named sink —
            # with --cdc-fanout the healthy consumers keep pace while
            # this one's position falls behind (the prodday timeline's
            # slow-consumer event)
            n_last, s_last = named[-1]
            named[-1] = (n_last, CountThrottleSink(s_last, args.cdc_slow_every))
        # an explicit --cdc-cursor names the cursor FILE and is used
        # verbatim (a restart must find the pre-existing cursor); the
        # fan-out path derives per-consumer files by suffixing it
        cursor_file = args.cdc_cursor or (
            (args.cdc_jsonl or args.file) + ".cursor"
        )
        if args.cdc_fanout and len(named) > 1:
            # one shared tail, one consumer (cursor + position) PER sink:
            # a slow sink pauses only itself (ingress/fanout.py)
            from tigerbeetle_tpu.ingress import CdcFanoutHub

            cdc_pump = CdcFanoutHub(
                replica, window=args.cdc_window,
                aof_path=args.aof or None,
            )
            for name, sink in named:
                cdc_pump.add_consumer(
                    name, sink, FileCursor(f"{cursor_file}.{name}"),
                    ack_interval=args.cdc_ack_interval,
                    commitments=args.commitment_interval > 0,
                )
        else:
            sink = (
                named[0][1] if len(named) == 1
                else _FanoutSink([s for _n, s in named])
            )
            cdc_pump = CdcPump(
                replica, sink, FileCursor(cursor_file),
                window=args.cdc_window,
                ack_interval=args.cdc_ack_interval,
                # the AOF (when on) is the deep-resume source: ops older
                # than the WAL ring replay through the oracle with exact
                # results
                aof_path=args.aof or None,
                commitments=args.commitment_interval > 0,
            )
        # attach BEFORE open(): single-replica recovery re-commits the
        # journal tail, and those redeliveries are exactly what the
        # cursor dedups — the pump must see them, not miss them
        cdc_pump.attach()
    statsd = emitter = None
    if args.statsd:
        # accepts `host`, `:port`, and `host:port` (a bare host used to
        # crash on int("") after rpartition)
        statsd = StatsD(*parse_addr(args.statsd))
        # batched emission: the WHOLE registry per flush, many metrics
        # per MTU-sized datagram, counters as deltas
        emitter = StatsDEmitter(statsd, metrics)
    boot("opening (superblock + snapshot + WAL recovery)")
    replica.open()
    boot("open done")
    if args.ingress:
        from tigerbeetle_tpu.ingress import IngressGateway

        gateway = IngressGateway(
            bus, replica, sessions_max=args.ingress_sessions_max
        )
        gateway.install()
        boot("ingress gateway installed")
    print(
        f"replica {args.replica}/{len(addresses)} listening on "
        f"{addresses[args.replica][0]}:{addresses[args.replica][1]} "
        f"(op={replica.op}, commit={replica.commit_min})",
        flush=True,
    )
    if args.backend != "native":
        # compile sentinel: serving starts here — any XLA compile past
        # this point is a hot-path event (device.compiles_post_warmup +
        # the SIGQUIT dump's event log). The dual warm path already
        # marked warm; this covers device/sharded backends too.
        from tigerbeetle_tpu.models.ledger import COMPILE_SENTINEL

        COMPILE_SENTINEL.mark_warm()
    if args.device_trace:
        if hasattr(replica.ledger, "start_device_trace"):
            replica.ledger.start_device_trace(
                args.device_trace, args.device_trace_s
            )
        else:
            print(
                f"--device-trace ignored: backend {args.backend!r} has "
                "no device-applier thread (use dual or native+device)",
                flush=True,
            )
    profile_path = os.environ.get("TB_PROFILE")
    prof = None
    if profile_path:
        # Profile the event loop; dump pstats on SIGTERM (the bench harness
        # terminates the server when the drive completes).
        import cProfile

        prof = cProfile.Profile()

    # event-loop cost accounting: busy wall time (pump + commit dispatch +
    # flush, never blocking selects or idle sleeps) over ops committed BY
    # THIS PROCESS (commit_min starts at the recovered commit number on
    # restart) — the per-batch loop cost the bench reports as
    # loop_us_per_batch. Registry-backed: the [stats] line and --statsd
    # read the same counters.
    loop_stats = metrics.group("loop", ("busy_s", "turns"))
    boot_commit = replica.commit_min

    def _on_term(_sig, _frm):
        # Emit observability counters for the bench harness (group-commit
        # hit rate etc.), then exit. The harness parses the [stats] line.
        import json as _json

        hz = getattr(replica.ledger, "hazards", None)
        stats = {
            "group": dict(replica.group_stats),
            # the fuse window the run ENDED at (autotune moves it): the
            # bench records this per segment next to the hit rate, so a
            # bad hit rate is attributable to the window it ran with
            "fuse": {
                "window_us": replica.fuse_window_ns // 1000,
                "autotune": replica.fuse_autotune,
            },
            # the conflict-wave planner's decision counters (plan_stats);
            # the "split" key name is the DEPRECATED dashboard surface —
            # the dict carries both the wave keys (waves/wave_dispatches/
            # residue_events/chain_len_max) and the legacy split keys
            "split": dict(hz.split_stats) if hz is not None else {},
            "pool_dropped": bus.pool.dropped,
            "loop": {
                "busy_s": round(loop_stats["busy_s"], 3),
                "turns": loop_stats["turns"],
                "us_per_batch": round(
                    loop_stats["busy_s"] * 1e6
                    / max(1, replica.commit_min - boot_commit), 1
                ),
            },
            # the full registry (counters/gauges/histogram percentile
            # snapshots): the bench harness and --statsd read the SAME
            # store this line is printed from
            "metrics": metrics.snapshot(),
            # per-request breakdowns of the slowest sampled requests
            # (latency.py): where THOSE requests' milliseconds went
            "latency_slowest": replica.latency.slowest(limit=8),
        }
        if flight is not None and flight.phase_log:
            # the scenario-phase timeline (prodday `mark` markers): when
            # each phase of the scripted run began, by the recorder clock
            stats["phases"] = flight.phase_log
        if replica.commitment_log is not None:
            # checkpoint state-commitment chain head + recent entries —
            # the same surface `inspect commitments` reads live
            stats["commitments"] = replica.commitment_log.stats_snapshot()
        if args.federation_regions:
            stats["federation"] = {
                "region": args.federation_region,
                "regions": args.federation_regions,
            }
        _lmod = sys.modules.get("tigerbeetle_tpu.models.ledger")
        if _lmod is not None:
            # compile-sentinel totals + bounded event log (post-warmup
            # compiles are the .jax_cache pathology, named)
            stats["compile_sentinel"] = _lmod.COMPILE_SENTINEL.snapshot()
        _da = getattr(replica.ledger, "device_anatomy", None)
        if _da is not None and _da.slowest():
            # dual mode: slowest sampled apply items, sub-leg breakdowns
            stats["device_slowest"] = _da.slowest(limit=8)
        if getattr(replica.ledger, "spill", None) is not None:
            stats["spill"] = dict(replica.ledger.spill.stats)
        if hash_log is not None:
            # record mode persists the stream; both modes report coverage
            # (check mode would already have died AT a divergent op)
            try:
                if hash_log.mode == "record":
                    hash_log.save()
                stats["hash_log"] = {
                    "mode": hash_log.mode,
                    "path": hash_log.path,
                    # coverage THIS RUN (check mode preloads `entries`
                    # from the recording — its length is not coverage)
                    "ops": hash_log.ops_seen,
                }
            except Exception as e:
                stats["hash_log"] = {"error": f"{type(e).__name__}: {e}"}
        if hasattr(replica.ledger, "finalize"):
            # dual mode: drain the device shadow, then the process's FIRST
            # d2h reads verify the device state bit-exact (after the
            # harness's clock has already stopped — the timed phase never
            # paid a device round trip). Never let verification failure
            # eat the [stats] line itself.
            try:
                replica.flush_commits()
                stats["device_shadow"] = replica.ledger.finalize()
            except Exception as e:
                stats["device_shadow"] = {
                    "verified": False,
                    "error": f"{type(e).__name__}: {e}",
                }
        print(f"[stats] {_json.dumps(stats)}", flush=True)
        if cdc_pump is not None:
            # finalize any in-flight commits (their replies are what the
            # stream encodes), then a bounded final drain + durable
            # cursor/sink flush — a slow sink must not hold up shutdown
            try:
                replica.flush_commits()
            except Exception:
                pass  # stream what already finalized
            cdc_pump.pump(budget_ops=1024)
            cdc_pump.flush()
            if hasattr(cdc_pump, "close"):
                cdc_pump.close()  # fan-out hub: every consumer's sink
            else:
                cdc_pump.sink.close()
        if args.trace:
            tracer.dump(args.trace)
        if emitter is not None:
            emitter.flush()  # final batched emission before exit
        if prof is not None:
            prof.disable()
            prof.dump_stats(profile_path)
        os._exit(0)

    signal.signal(signal.SIGTERM, _on_term)

    def _on_quit(_sig, _frm):
        # Hang diagnosis (kill -QUIT <pid>): a WEDGED server dumps its
        # evidence and KEEPS RUNNING (the operator decides what to do
        # next) — before this, SIGQUIT killed the process with nothing.
        # Dumped: every thread's stack (faulthandler), the consensus
        # state the [debug] line would show, and — when tracing is on —
        # the trace ring incl. still-open spans to <trace>.quit.json
        # (an open span IS the wedge's name).
        import json as _json

        metrics.counter("trace.sigquit_dumps").add()
        sys.stderr.write(
            f"[quit] status={replica.status} view={replica.view} "
            f"op={replica.op} commit={replica.commit_min} "
            f"pipeline={sorted(replica.pipeline)} "
            f"inflight={len(replica._inflight)} "
            f"wanted={sorted(replica._repair_wanted)}\n"
        )
        faulthandler.dump_traceback(file=sys.stderr)
        if tracer.enabled:
            open_spans = [
                e for e in tracer.events_ordered() if e["ph"] == "B"
            ]
            sys.stderr.write(
                f"[quit] {len(open_spans)} open span(s): "
                + ", ".join(
                    f"{e['name']}{e.get('args') or ''}"
                    for e in open_spans[:16]
                )
                + "\n"
            )
            quit_path = f"{args.trace}.quit.json"
            try:
                tracer.dump(quit_path)
                sys.stderr.write(f"[quit] trace ring -> {quit_path}\n")
            except OSError as e:
                sys.stderr.write(f"[quit] trace dump failed: {e}\n")
        else:
            sys.stderr.write(
                "[quit] tracing off (start with --trace <path> for the "
                "span ring)\n"
            )
        snap = {
            "status": replica.status, "view": replica.view,
            "op": replica.op, "commit_min": replica.commit_min,
            "metrics": metrics.snapshot(),
            # the incident evidence the cumulative snapshot cannot give:
            # per-request breakdowns of the slowest sampled requests and
            # the flight recorder's last minute of per-interval history
            "latency_slowest": replica.latency.slowest(limit=8),
        }
        _lmod = sys.modules.get("tigerbeetle_tpu.models.ledger")
        if _lmod is not None:
            # a wedged applier's first suspect: a post-warmup compile
            # stalling the loop — the event log names the signature
            snap["compile_sentinel"] = _lmod.COMPILE_SENTINEL.snapshot()
        _da = getattr(replica.ledger, "device_anatomy", None)
        if _da is not None and _da.slowest():
            snap["device_slowest"] = _da.slowest(limit=8)
        if flight is not None:
            snap["history"] = flight.history(last=60)
            if flight.phase_log:
                # which scenario phase each slice of that history ran
                # under (prodday `mark` markers)
                snap["phases"] = flight.phase_log
        sys.stderr.write(f"[quit] stats {_json.dumps(snap)}\n")
        sys.stderr.flush()

    signal.signal(signal.SIGQUIT, _on_quit)
    if prof is not None:
        prof.enable()

    debug = bool(os.environ.get("TB_DEBUG"))
    tick_s = process_cfg.tick_ms / 1000.0
    last_tick = time.monotonic()
    last_debug = time.monotonic()
    last_statsd = time.monotonic()
    last_flight = time.monotonic()
    last_commit = replica.commit_min
    while True:
        # With async commits in flight — or a fuse window holding a short
        # run open for more arrivals — poll (timeout=0) so a quiet wire
        # flushes replies immediately and the window expiry is checked
        # every turn; otherwise block one tick.
        busy = bool(replica._inflight) or replica._fuse_started is not None
        t0 = time.monotonic()
        n = bus.pump(timeout=0.0 if busy else tick_s)
        # every turn (not only n > 0): same-turn arrivals fuse into a
        # group, and an expired fuse window must dispatch promptly
        replica.pump_commits()
        if cdc_pump is not None:
            # bounded change-stream progress OFF the commit path: one op
            # per turn while the wire is busy (an 8190-record encode is
            # real host time), a larger bite when idle. Not counted into
            # loop busy_s — that accounts the commit pipeline the bench's
            # loop_us_per_batch quotes.
            cdc_pump.pump(budget_ops=1 if busy else 8)
        if busy:
            loop_stats.add("busy_s", time.monotonic() - t0)
            loop_stats.add("turns")
        if n == 0 and busy:
            # Bus idle: flush once the whole window's device results are
            # computed — ONE device->host round trip then drains every
            # in-flight batch (fetching earlier would pay a round trip
            # per batch on high-latency transports).
            if replica.commits_ready():
                t0 = time.monotonic()
                replica.flush_commits()
                loop_stats.add("busy_s", time.monotonic() - t0)
            elif replica._inflight:
                time.sleep(0.0002)
        now = time.monotonic()
        if now - last_tick >= tick_s:
            last_tick = now
            replica.tick()
            # registry updates are unconditional — the [stats] snapshot
            # and bench server_metrics carry them with or without statsd
            if replica.commit_min != last_commit:
                metrics.counter("server.ops_committed").add(
                    replica.commit_min - last_commit
                )
                metrics.gauge("server.commit_min").set(replica.commit_min)
                last_commit = replica.commit_min
            # batched flush on a ~1s cadence: the WHOLE registry rides a
            # handful of MTU-sized datagrams instead of one packet per
            # metric per tick
            if emitter is not None and now - last_statsd >= 1.0:
                last_statsd = now
                emitter.flush()
            # flight recorder: one time-series entry per interval —
            # counter deltas + windowed histogram percentiles, the
            # history `inspect live --watch` and the SIGQUIT dump read
            if flight is not None and now - last_flight >= args.flight_interval_s:
                last_flight = now
                flight.record(now)
        if debug and now - last_debug >= 1.0:
            last_debug = now
            print(
                f"[debug] status={replica.status} view={replica.view} "
                f"op={replica.op} commit={replica.commit_min} "
                f"pipeline={sorted(replica.pipeline)} "
                f"wanted={sorted(replica._repair_wanted)} "
                f"conns={sorted(str(k) if k < 1000 else 'client' for k in bus.conns)}",
                flush=True,
            )


def cmd_chaos(args) -> int:
    import json as _json

    from tigerbeetle_tpu.testing.chaos import CHAOS_ACTIONS, run_chaos

    if args.kill_cluster:
        from tigerbeetle_tpu.federation.live import run_federation_chaos

        def fed_log(*a):
            print("[chaos]", *a, file=sys.stderr, flush=True)

        report = run_federation_chaos(
            regions=args.federation_regions,
            replica_count=args.replicas,
            payments=args.payments,
            commitment_interval=args.commitment_interval,
            restart_after_s=args.restart_after_s,
            backend=args.backend, seed=args.seed,
            deadline_s=args.deadline_s,
            jax_platform=None,  # the CLI inherits the ambient platform
            log=fed_log,
        )
        if args.json:
            with open(args.json, "w") as f:
                _json.dump(report, f, indent=1, sort_keys=True)
        print(_json.dumps(report, indent=1, sort_keys=True))
        ok = (
            report["conservation"]["ok"]
            and all(
                v["checked"] > 0 for v in report["stream_verify"].values()
            )
        )
        return 0 if ok else 1

    faults = tuple(f for f in args.faults.split(",") if f)
    for f in faults:
        if f not in CHAOS_ACTIONS:
            flags.fatal(
                f"unknown fault {f!r} ({' | '.join(CHAOS_ACTIONS)})"
            )

    def log(*a):
        print("[chaos]", *a, file=sys.stderr, flush=True)

    report = run_chaos(
        n_sessions=args.sessions, conns=args.conns,
        n_accounts=args.accounts,
        events_per_batch=args.events_per_batch,
        batches_per_session=args.batches_per_session,
        replica_count=args.replicas, backend=args.backend,
        faults=faults, restart_after_s=args.restart_after_s,
        gray_s=args.gray_s, disk_fault_on_restart=args.disk_fault,
        ingress=args.ingress, seed=args.seed, deadline_s=args.deadline_s,
        jax_platform=None,  # the CLI inherits the ambient platform
        log=log,
    )
    if args.json:
        with open(args.json, "w") as f:
            _json.dump(report, f, indent=1, sort_keys=True)
    print(_json.dumps(report, indent=1, sort_keys=True))
    ok = report["lost_events"] == 0 and report["conservation_ok"]
    return 0 if ok else 1


def cmd_cdc(args) -> int:
    """Replay the AOF's change stream into a sink from the consumer's
    cursor. One shot: runs to the end of the log (or --limit), acks the
    cursor, exits — the operator bootstrap/backfill path; live tailing is
    `start --cdc-jsonl/...`."""
    from tigerbeetle_tpu.cdc import (
        AofReplaySource,
        FileCursor,
        JsonlFileSink,
        StdoutSink,
        UdpSink,
        encode_batch,
        gap_record,
        record_line,
    )
    from tigerbeetle_tpu.statsd import parse_addr

    if args.sink == "stdout":
        sink = StdoutSink()
    elif args.sink.startswith("jsonl:"):
        sink = JsonlFileSink(args.sink[len("jsonl:"):])
    elif args.sink.startswith("udp:"):
        sink = UdpSink(*parse_addr(args.sink[len("udp:"):]))
    else:
        flags.fatal(f"unknown --sink {args.sink!r} "
                    "(stdout | jsonl:<path> | udp:host[:port])")
    cursor = FileCursor(
        args.cursor or f"{args.file}.{args.consumer}.cursor"
    )
    acked_op, _ = cursor.load()
    source = AofReplaySource(args.file)
    ops = records = 0
    op = acked_op + 1
    last = None
    while not args.limit or ops < args.limit:
        got = source.read(op)
        if got is None:
            # an AOF hole (ops this replica never executed — a state-sync
            # jump): declare it and continue from where the log resumes
            resume = source.next_available()
            if resume is None:
                break  # end of log
            if not sink.emit_lines([record_line(gap_record(op, resume - 1))]):
                break
            op = resume
            continue
        header, body, reply = got
        recs = encode_batch(header, body, reply)
        if recs and not sink.emit_lines([record_line(r) for r in recs]):
            break  # a refusing sink ends the one-shot run; cursor holds
        records += len(recs)
        ops += 1
        last = header
        op += 1
    if last is not None:
        cursor.ack(last.op, last.checksum)
    sink.flush()
    sink.close()
    print(
        f"cdc: {records} records over {ops} ops "
        f"(consumer {args.consumer!r}, cursor at op {last.op if last else acked_op})",
        file=sys.stderr,
    )
    return 0


def cmd_inspect(args) -> int:
    import json as _json

    from tigerbeetle_tpu import inspect as _inspect
    from tigerbeetle_tpu.constants import ConfigCluster

    def emit(topic: str, report) -> None:
        if args.json:
            _json.dump(report, sys.stdout, indent=1, sort_keys=True,
                       default=str)
            sys.stdout.write("\n")
        else:
            _inspect.render(topic, report, sys.stdout)

    topics = ("superblock", "wal", "replies", "grid", "lsm",
              "client-table", "all", "live", "commitments")
    if args.topic not in topics:
        flags.fatal(
            f"unknown inspect topic {args.topic!r} ({' | '.join(topics)})"
        )
    if args.topic == "commitments":
        if args.stream:
            # external-consumer verify: replay the stream, re-derive the
            # chain, reject tampering at the exact checkpoint
            report = _inspect.verify_commitment_stream(args.stream)
            emit("commitments", report)
            return 0 if report["ok"] else 1
        if args.addresses:
            host, sep, port = args.addresses.strip().rpartition(":")
            if not sep or not port.isdigit():
                flags.fatal("inspect commitments needs --addresses host:port")
            live = _inspect.inspect_live(host or "127.0.0.1", int(port))
            report = _inspect.commitments_from_stats(live)
            emit("commitments", report)
            return 0 if report.get("enabled") else 1
        if not args.file:
            flags.fatal(
                "inspect commitments needs a data file, --addresses, or "
                "--stream"
            )
        cluster_cfg = ConfigCluster(
            clients_max=args.clients_max,
            client_reply_slots=args.client_reply_slots,
        )
        storage = _inspect.open_storage(
            args.file, cluster_cfg, forest_blocks=args.forest_blocks
        )
        try:
            report = _inspect.inspect_commitments_offline(storage)
        finally:
            storage.close()
        emit("commitments", report)
        return 0 if report.get("enabled") else 1
    if args.topic == "live":
        # a replica has no default port, so one is mandatory (`:3001`
        # and `host:3001` both work; statsd.parse_addr is wrong here —
        # its bare-host default is the statsd port)
        host, sep, port = args.addresses.strip().rpartition(":")
        if not sep or not port.isdigit():
            flags.fatal("inspect live needs --addresses host:port")
        if args.watch > 0:
            return _inspect.watch_live(
                host or "127.0.0.1", int(port), interval_s=args.watch,
                count=args.watch_count, out=sys.stdout,
                as_json=args.json,
            )
        report = _inspect.inspect_live(host or "127.0.0.1", int(port))
        emit("live", report)
        return 0

    if not args.file:
        flags.fatal(f"inspect {args.topic} needs a data file path")
    cluster_cfg = ConfigCluster(
        clients_max=args.clients_max,
        client_reply_slots=args.client_reply_slots,
    )
    storage = _inspect.open_storage(
        args.file, cluster_cfg, forest_blocks=args.forest_blocks
    )
    try:
        sb = _inspect.inspect_superblock(storage)
        state = sb["state"]
        if args.topic == "superblock":
            emit("superblock", sb)
        elif args.topic == "wal":
            if args.op >= 0:
                emit("wal-op", _inspect.inspect_wal_op(
                    storage, cluster_cfg, args.op
                ))
            else:
                report = _inspect.inspect_wal(storage, cluster_cfg, state)
                if args.slot >= 0:
                    report["slots"] = [
                        s for s in report["slots"]
                        if s["slot"] == args.slot
                    ]
                emit("wal", report)
        elif args.topic == "replies":
            emit("replies", _inspect.inspect_replies(storage, cluster_cfg))
        elif args.topic == "grid":
            emit("grid", _inspect.inspect_grid(storage, cluster_cfg, state))
        elif args.topic == "lsm":
            emit("lsm", _inspect.inspect_lsm(storage, cluster_cfg, state))
        elif args.topic == "client-table":
            emit("client-table",
                 _inspect.inspect_client_table(storage, state))
        else:  # "all" (the topic was validated above)
            for topic, report in (
                ("superblock", sb),
                ("wal", _inspect.inspect_wal(storage, cluster_cfg, state)),
                ("replies",
                 _inspect.inspect_replies(storage, cluster_cfg)),
                ("grid",
                 _inspect.inspect_grid(storage, cluster_cfg, state)),
                ("lsm", _inspect.inspect_lsm(storage, cluster_cfg, state)),
                ("client-table",
                 _inspect.inspect_client_table(storage, state)),
            ):
                if not args.json:
                    sys.stdout.write(f"== {topic} ==\n")
                emit(topic, report)
    finally:
        storage.close()
    return 0


def cmd_repl(args) -> int:
    from tigerbeetle_tpu.repl import Repl

    addresses = _parse_addresses(args.addresses)
    repl = Repl(addresses, cluster_id=args.cluster)
    return repl.run(sys.stdin, echo=not sys.stdin.isatty())


USAGE = """usage: tigerbeetle_tpu <command> [flags] [file]

commands:
  format   create a fresh data file
  start    run a replica
  version  print version
  repl     interactive client (alias: client)
  cdc      replay an AOF's change stream into a sink (cursor resume)
  inspect  decode a data file offline / read a live server's stats
  chaos    live-cluster chaos run (kill/gray/reset faults + verification)
"""

COMMANDS = {
    "format": (FormatArgs, cmd_format),
    "start": (StartArgs, cmd_start),
    "repl": (ReplArgs, cmd_repl),
    "client": (ReplArgs, cmd_repl),
    "cdc": (CdcArgs, cmd_cdc),
    "inspect": (InspectArgs, cmd_inspect),
    "chaos": (ChaosArgs, cmd_chaos),
}


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help", "help"):
        print(USAGE, end="")
        return 0 if argv else 1
    command, rest = argv[0], argv[1:]
    if command == "version":
        print(f"tigerbeetle_tpu {VERSION}")
        return 0
    if command not in COMMANDS:
        flags.fatal(f"unknown command {command!r}\n{USAGE}")
    spec, fn = COMMANDS[command]
    return fn(flags.parse(spec, rest)) or 0


if __name__ == "__main__":
    raise SystemExit(main())
