"""Multi-chip ledger: the HBM tables sharded over a jax.sharding.Mesh.

The reference scales one replica's state machine only vertically (one core,
one NUMA node — reference: src/vsr/replica.zig single-threaded event loop).
The TPU-native design shards the account and transfer hash tables across
chips of ONE replica over ICI — consensus replication between replicas stays
host-level and is orthogonal (SURVEY.md §5.8).

Layout: every table column is [n_shards, local_rows] sharded on axis 0 over
mesh axis "shard". A key's owner shard is a second, independent hash
(owner_u128); within the owner it probes that shard's local open-addressing
table. A commit step runs under shard_map:

1. Each shard probes its local tables for ALL lanes, masks hits by ownership,
   and the per-lane rows are combined with psum over ICI (exactly one shard
   contributes non-zero data per found lane).
2. Validation (models/validate.py ladders) is computed replicated — it is pure
   elementwise math over the psum'd rows, identical on every shard.
3. Application is local: each shard scatter-applies balance deltas and row
   inserts only for keys it owns.

This multi-chip tier currently executes the vectorized fast path (no-flag and
pending-only batches). Hazard batches (linked chains, post/void, balancing,
duplicate ids, limit accounts, overflow risk) are detected on device and
reported to the host, which must route them to the single-chip serial tier;
the sharded serial tier is future work.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tigerbeetle_tpu.constants import ConfigProcess
from tigerbeetle_tpu.models import validate
from tigerbeetle_tpu.models.ledger import (
    _SLOW_FLAGS,
    _U32_COLS_ACCT,
    _U32_COLS_XFER,
    _U64_COLS_ACCT,
    _U64_COLS_XFER,
    _apply_digits,
    _has_duplicate_ids,
    _next_pow2,
    accounts_to_batch,
    transfers_to_batch,
)
from tigerbeetle_tpu.models.validate import F_PENDING
from tigerbeetle_tpu.ops import hashtable as ht
from tigerbeetle_tpu.types import Operation

U64 = jnp.uint64
U32 = jnp.uint32
I32 = jnp.int32


_OWNER_MIX = jnp.uint64(0xD6E8FEB86659FD93)


def owner_u128(key_lo, key_hi, n_shards: int):
    """Owner shard of a key — an independent hash from the slot hash."""
    x = (key_lo ^ jnp.uint64(0xA5A5A5A5A5A5A5A5)) * _OWNER_MIX
    x = x ^ (key_hi * _OWNER_MIX) ^ (x >> jnp.uint64(29))
    x = x * jnp.uint64(0x94D049BB133111EB)
    x = x ^ (x >> jnp.uint64(32))
    return (x % jnp.uint64(n_shards)).astype(I32)


def init_sharded_state(mesh: Mesh, process: ConfigProcess) -> dict:
    """Tables of [n_shards, local_rows] sharded over mesh axis "shard".
    local capacity = 2^account_slots_log2 etc. PER SHARD."""
    n = mesh.devices.size
    a_rows = (1 << process.account_slots_log2) + 1
    t_rows = (1 << process.transfer_slots_log2) + 1
    sh = NamedSharding(mesh, P("shard", None))
    sc = NamedSharding(mesh, P())

    def col(rows, dt):
        return jax.device_put(jnp.zeros((n, rows), dtype=dt), sh)

    acct = {c: col(a_rows, U64) for c in _U64_COLS_ACCT}
    acct.update({c: col(a_rows, U32) for c in _U32_COLS_ACCT})
    xfer = {c: col(t_rows, U64) for c in _U64_COLS_XFER}
    xfer.update({c: col(t_rows, U32) for c in _U32_COLS_XFER})
    return {
        "acct": acct,
        "xfer": xfer,
        "acct_claim": jax.device_put(jnp.full((n, a_rows), ht.CLAIM_FREE, dtype=U32), sh),
        "xfer_claim": jax.device_put(jnp.full((n, t_rows), ht.CLAIM_FREE, dtype=U32), sh),
        "commit_ts": jax.device_put(jnp.uint64(0), sc),
        "acct_count": jax.device_put(jnp.uint64(0), sc),
        "xfer_count": jax.device_put(jnp.uint64(0), sc),
    }


def _psum_row(row: dict, contribute, axis: str) -> dict:
    """Combine per-shard masked rows: exactly one shard contributes per lane."""
    out = {}
    for k, v in row.items():
        masked = jnp.where(contribute, v, jnp.zeros_like(v))
        out[k] = jax.lax.psum(masked, axis)
    return out


class ShardedLedgerKernels:
    """shard_map commit kernels over a 1-D "shard" mesh axis."""

    def __init__(self, mesh: Mesh, process: ConfigProcess):
        self.mesh = mesh
        self.n_shards = mesh.devices.size
        self.process = process
        self.a_log2 = process.account_slots_log2
        self.t_log2 = process.transfer_slots_log2
        self.a_dump = jnp.int32(1 << self.a_log2)
        self.t_dump = jnp.int32(1 << self.t_log2)

        state_spec = jax.tree_util.tree_map(lambda _: P("shard", None), {
            "acct": {c: 0 for c in (*_U64_COLS_ACCT, *_U32_COLS_ACCT)},
            "xfer": {c: 0 for c in (*_U64_COLS_XFER, *_U32_COLS_XFER)},
            "acct_claim": 0, "xfer_claim": 0,
        })
        state_spec["commit_ts"] = P()
        state_spec["acct_count"] = P()
        state_spec["xfer_count"] = P()
        ev_spec = P()

        self.commit_transfers = jax.jit(
            shard_map(
                self._commit_transfers_shard,
                mesh=mesh,
                in_specs=(state_spec, ev_spec, P(), P()),
                out_specs=(state_spec, P(), P()),
                check_rep=False,
            ),
            donate_argnums=(0,),
        )
        self.commit_accounts = jax.jit(
            shard_map(
                self._commit_accounts_shard,
                mesh=mesh,
                in_specs=(state_spec, ev_spec, P(), P()),
                out_specs=(state_spec, P(), P()),
                check_rep=False,
            ),
            donate_argnums=(0,),
        )
        self.lookup_accounts = jax.jit(
            shard_map(
                self._lookup_accounts_shard,
                mesh=mesh,
                in_specs=(state_spec, ev_spec),
                out_specs=(P(), P()),
                check_rep=False,
            )
        )
        self.lookup_transfers = jax.jit(
            shard_map(
                self._lookup_transfers_shard,
                mesh=mesh,
                in_specs=(state_spec, ev_spec),
                out_specs=(P(), P()),
                check_rep=False,
            )
        )

    # -- sharded lookup: local probe + ownership mask + psum --

    def _find(self, tbl, key_lo, key_hi, log2, my_shard):
        own = owner_u128(key_lo, key_hi, self.n_shards) == my_shard
        slot, found_l = ht.lookup(key_lo, key_hi, tbl["key_lo"], tbl["key_hi"], log2)
        mine = own & found_l
        found = jax.lax.psum(mine.astype(U32), "shard") > 0
        row = _psum_row({k: v[slot] for k, v in tbl.items()}, mine, "shard")
        return slot, own, mine, found, row

    def _commit_transfers_shard(self, state, ev, n, timestamp):
        my = jax.lax.axis_index("shard")
        acct = {k: v[0] for k, v in state["acct"].items()}  # local [rows]
        xfer = {k: v[0] for k, v in state["xfer"].items()}
        acct_claim = state["acct_claim"][0]
        xfer_claim = state["xfer_claim"][0]

        B = ev["flags"].shape[0]
        lane = jnp.arange(B, dtype=I32)
        valid = lane < n
        ts_vec = timestamp - n.astype(U64) + lane.astype(U64) + jnp.uint64(1)
        ev_a = {**ev, "ts": ts_vec}

        dr_slot, dr_own, dr_mine, dr_found, dr = self._find(
            acct, ev["dr_lo"], ev["dr_hi"], self.a_log2, my
        )
        cr_slot, cr_own, cr_mine, cr_found, cr = self._find(
            acct, ev["cr_lo"], ev["cr_hi"], self.a_log2, my
        )
        ex_slot, ex_own, ex_mine, ex_found, ex = self._find(
            xfer, ev["id_lo"], ev["id_hi"], self.t_log2, my
        )

        r0 = jnp.where(ev["ts"] != 0, jnp.uint32(3), jnp.uint32(0))
        r0 = validate.transfer_common(ev, r0)
        r, amt_lo, amt_hi = validate.validate_simple_transfer(
            r0, ev_a, dr, cr, dr_found, cr_found, ex, ex_found
        )
        r = jnp.where(valid, r, jnp.uint32(0))
        ok = valid & (r == 0)

        # Hazards (replicated).
        h_flags = jnp.any(valid & ((ev["flags"] & jnp.uint32(_SLOW_FLAGS)) != 0))
        h_dup = _has_duplicate_ids(ev["id_lo"], ev["id_hi"], valid)
        h_amt = jnp.any(ok & (ev["amt_hi"] != 0))
        limit_bits = jnp.uint32(validate.A_DR_LIMIT | validate.A_CR_LIMIT)
        h_limit = jnp.any(ok & (((dr["flags"] | cr["flags"]) & limit_bits) != 0))

        # Local balance-delta accumulation: only lanes whose target account
        # this shard owns (dr/cr row present locally).
        pending = ok & ((ev["flags"] & jnp.uint32(F_PENDING)) != 0)
        posted = ok & ~pending
        mask32 = jnp.uint64(0xFFFFFFFF)
        d0 = amt_lo & mask32
        d1 = amt_lo >> jnp.uint64(32)
        a_rows = (1 << self.a_log2) + 1
        overflow = jnp.zeros((), dtype=bool)
        new_bal = {}
        for colname, cond, slot, mine in (
            ("dp", pending, dr_slot, dr_mine),
            ("dpo", posted, dr_slot, dr_mine),
            ("cp", pending, cr_slot, cr_mine),
            ("cpo", posted, cr_slot, cr_mine),
        ):
            w = jnp.where(cond & mine, slot, self.a_dump)
            acc0 = jnp.zeros(a_rows, dtype=U64).at[w].add(d0)
            acc1 = jnp.zeros(a_rows, dtype=U64).at[w].add(d1)
            lo, hi, over = _apply_digits(
                acct[colname + "_lo"], acct[colname + "_hi"], acc0, acc1
            )
            new_bal[colname + "_lo"] = lo
            new_bal[colname + "_hi"] = hi
            overflow = overflow | jnp.any(over[: 1 << self.a_log2])
        overflow = jax.lax.psum(overflow.astype(U32), "shard") > 0
        hazard = h_flags | h_dup | h_amt | h_limit | overflow

        # Apply (no-op when hazard: host re-routes the batch; predicate all
        # writes so the fast application is safe to discard).
        apply_ok = ok & ~hazard
        acct2 = {**acct}
        for colname in ("dp", "dpo", "cp", "cpo"):
            for part in ("_lo", "_hi"):
                acct2[colname + part] = jnp.where(hazard, acct[colname + part],
                                                  new_bal[colname + part])

        own_id = owner_u128(ev["id_lo"], ev["id_hi"], self.n_shards) == my
        ins = apply_ok & own_id
        xfer2 = dict(xfer)
        slots, k_lo, k_hi, xfer_claim = ht.insert_slots(
            ev["id_lo"], ev["id_hi"], ins,
            xfer2["key_lo"], xfer2["key_hi"], xfer_claim, self.t_log2,
        )
        xfer2["key_lo"], xfer2["key_hi"] = k_lo, k_hi
        w = jnp.where(ins, slots, self.t_dump)
        for col, val in (
            ("dr_lo", ev["dr_lo"]), ("dr_hi", ev["dr_hi"]),
            ("cr_lo", ev["cr_lo"]), ("cr_hi", ev["cr_hi"]),
            ("amt_lo", amt_lo), ("amt_hi", amt_hi),
            ("pid_lo", ev["pid_lo"]), ("pid_hi", ev["pid_hi"]),
            ("ud128_lo", ev["ud128_lo"]), ("ud128_hi", ev["ud128_hi"]),
            ("ud64", ev["ud64"]), ("ud32", ev["ud32"]),
            ("timeout", ev["timeout"]), ("ledger", ev["ledger"]),
            ("code", ev["code"]), ("flags", ev["flags"]),
            ("ts", ts_vec), ("fulfill", jnp.zeros_like(ev["ud32"])),
        ):
            xfer2[col] = xfer2[col].at[w].set(val)

        any_ok = jnp.any(apply_ok)
        last_ts = jnp.max(jnp.where(apply_ok, ts_vec, jnp.uint64(0)))
        new_state = {
            "acct": {k: v[None] for k, v in acct2.items()},
            "xfer": {k: v[None] for k, v in xfer2.items()},
            "acct_claim": acct_claim[None],
            "xfer_claim": xfer_claim[None],
            "commit_ts": jnp.where(any_ok, last_ts, state["commit_ts"]),
            "acct_count": state["acct_count"],
            "xfer_count": state["xfer_count"] + jnp.sum(apply_ok).astype(U64),
        }
        return new_state, r, hazard

    def _commit_accounts_shard(self, state, ev, n, timestamp):
        my = jax.lax.axis_index("shard")
        acct = {k: v[0] for k, v in state["acct"].items()}
        acct_claim = state["acct_claim"][0]

        B = ev["flags"].shape[0]
        lane = jnp.arange(B, dtype=I32)
        valid = lane < n
        ts_vec = timestamp - n.astype(U64) + lane.astype(U64) + jnp.uint64(1)

        ex_slot, ex_own, ex_mine, ex_found, ex = self._find(
            acct, ev["id_lo"], ev["id_hi"], self.a_log2, my
        )
        r0 = jnp.where(ev["ts"] != 0, jnp.uint32(3), jnp.uint32(0))
        r = validate.validate_create_account(r0, ev, ex, ex_found)
        r = jnp.where(valid, r, jnp.uint32(0))
        ok = valid & (r == 0)

        h_flags = jnp.any(valid & ((ev["flags"] & jnp.uint32(validate.A_LINKED)) != 0))
        h_dup = _has_duplicate_ids(ev["id_lo"], ev["id_hi"], valid)
        hazard = h_flags | h_dup

        own_id = owner_u128(ev["id_lo"], ev["id_hi"], self.n_shards) == my
        ins = ok & ~hazard & own_id
        acct2 = dict(acct)
        slots, k_lo, k_hi, acct_claim = ht.insert_slots(
            ev["id_lo"], ev["id_hi"], ins,
            acct2["key_lo"], acct2["key_hi"], acct_claim, self.a_log2,
        )
        acct2["key_lo"], acct2["key_hi"] = k_lo, k_hi
        w = jnp.where(ins, slots, self.a_dump)
        for col, val in (
            ("dp_lo", ev["dp_lo"]), ("dp_hi", ev["dp_hi"]),
            ("dpo_lo", ev["dpo_lo"]), ("dpo_hi", ev["dpo_hi"]),
            ("cp_lo", ev["cp_lo"]), ("cp_hi", ev["cp_hi"]),
            ("cpo_lo", ev["cpo_lo"]), ("cpo_hi", ev["cpo_hi"]),
            ("ud128_lo", ev["ud128_lo"]), ("ud128_hi", ev["ud128_hi"]),
            ("ud64", ev["ud64"]), ("ud32", ev["ud32"]),
            ("ledger", ev["ledger"]), ("code", ev["code"]),
            ("flags", ev["flags"]), ("ts", ts_vec),
        ):
            acct2[col] = acct2[col].at[w].set(val)

        apply_ok = ok & ~hazard
        any_ok = jnp.any(apply_ok)
        last_ts = jnp.max(jnp.where(apply_ok, ts_vec, jnp.uint64(0)))
        new_state = {
            "acct": {k: v[None] for k, v in acct2.items()},
            "xfer": state["xfer"],
            "acct_claim": acct_claim[None],
            "xfer_claim": state["xfer_claim"],
            "commit_ts": jnp.where(any_ok, last_ts, state["commit_ts"]),
            "acct_count": state["acct_count"] + jnp.sum(apply_ok).astype(U64),
            "xfer_count": state["xfer_count"],
        }
        return new_state, r, hazard

    def _lookup_accounts_shard(self, state, ids):
        my = jax.lax.axis_index("shard")
        acct = {k: v[0] for k, v in state["acct"].items()}
        _, _, _, found, row = self._find(acct, ids["id_lo"], ids["id_hi"], self.a_log2, my)
        return found, row

    def _lookup_transfers_shard(self, state, ids):
        my = jax.lax.axis_index("shard")
        xfer = {k: v[0] for k, v in state["xfer"].items()}
        _, _, _, found, row = self._find(xfer, ids["id_lo"], ids["id_hi"], self.t_log2, my)
        return found, row


class ShardedLedger:
    """Host wrapper over the sharded kernels (fast-tier batches only; hazard
    batches raise for now — route them to the single-chip serial tier)."""

    def __init__(self, mesh: Mesh, process: ConfigProcess):
        self.mesh = mesh
        self.process = process
        self.kernels = ShardedLedgerKernels(mesh, process)
        self.state = init_sharded_state(mesh, process)

    def execute_dense(self, operation, timestamp: int, events) -> list[int]:
        from tigerbeetle_tpu import types as t

        n = len(events)
        n_pad = _next_pow2(n)
        if operation == Operation.create_transfers:
            arr = events if isinstance(events, np.ndarray) else t.transfers_to_np(events)
            batch = transfers_to_batch(arr, n_pad)
            fn = self.kernels.commit_transfers
        elif operation == Operation.create_accounts:
            arr = events if isinstance(events, np.ndarray) else t.accounts_to_np(events)
            batch = accounts_to_batch(arr, n_pad)
            fn = self.kernels.commit_accounts
        else:
            raise AssertionError(operation)
        new_state, results, hazard = fn(
            self.state, batch, jnp.int32(n), jnp.uint64(timestamp)
        )
        # The old state was donated; the kernel predicates every write on
        # ~hazard so new_state is content-identical to the old on hazard.
        self.state = new_state
        if bool(hazard):
            raise NotImplementedError(
                "hazard batch on the sharded tier: route to the single-chip "
                "serial kernel (sharded serial tier is future work)"
            )
        return [int(x) for x in np.asarray(results)[:n]]
