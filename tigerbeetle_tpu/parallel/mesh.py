"""Multi-chip ledger: the HBM tables sharded over a jax.sharding.Mesh.

The reference scales one replica's state machine only vertically (one core,
one NUMA node — reference: src/vsr/replica.zig single-threaded event loop).
The TPU-native design shards the account and transfer hash tables across
chips of ONE replica over ICI — consensus replication between replicas stays
host-level and is orthogonal (SURVEY.md §5.8).

Layout: wire-row tables of [n_shards, local_rows, 32] u32 sharded on axis 0
over mesh axis "shard". A key's owner shard is a second, independent hash
(owner_u128); within the owner it probes that shard's local open-addressing
table. A commit step runs under shard_map:

1. Each shard probes its local tables for ALL lanes, masks hits by ownership,
   and the per-lane 128-byte rows are combined with one psum over ICI
   (exactly one shard contributes non-zero data per found lane).
2. Validation (models/validate.py ladders) is computed replicated — it is
   pure elementwise math over the psum'd rows, identical on every shard.
3. Application is local: each shard digit-accumulates balance deltas and
   inserts rows only for keys it owns.

This multi-chip tier currently executes the vectorized fast path (no-flag and
pending-only batches). Hazard batches (linked chains, post/void, balancing,
duplicate ids, limit accounts, overflow risk) are detected on device and
reported to the host, which must route them to the single-chip serial tier;
the sharded serial tier is future work.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tigerbeetle_tpu.constants import ConfigProcess
from tigerbeetle_tpu.models import validate
from tigerbeetle_tpu.models.ledger import (
    ROW_WORDS,
    _SLOW_FLAGS,
    _amount_digits,
    _combined_overflow,
    _fold_digits,
    _has_duplicate_ids,
    _next_pow2,
    _set_ts_words,
    accounts_to_batch,
    key4_from_fields,
    transfers_to_batch,
    unpack_account,
    unpack_transfer,
)
from tigerbeetle_tpu.models.validate import F_PENDING
from tigerbeetle_tpu.ops import hashtable as ht
from tigerbeetle_tpu.types import Operation

U64 = jnp.uint64
U32 = jnp.uint32
I32 = jnp.int32

_OWNER_MIX = jnp.uint64(0xD6E8FEB86659FD93)


def owner_of_key4(key4, n_shards: int):
    """Owner shard of a key — an independent hash from the slot hash."""
    k = key4.astype(U64)
    lo = k[..., 0] | (k[..., 1] << jnp.uint64(32))
    hi = k[..., 2] | (k[..., 3] << jnp.uint64(32))
    x = (lo ^ jnp.uint64(0xA5A5A5A5A5A5A5A5)) * _OWNER_MIX
    x = x ^ (hi * _OWNER_MIX) ^ (x >> jnp.uint64(29))
    x = x * jnp.uint64(0x94D049BB133111EB)
    x = x ^ (x >> jnp.uint64(32))
    return (x % jnp.uint64(n_shards)).astype(I32)


def init_sharded_state(mesh: Mesh, process: ConfigProcess) -> dict:
    """Tables of [n_shards, local_rows, 32] sharded over mesh axis "shard".
    local capacity = 2^account_slots_log2 etc. PER SHARD."""
    n = mesh.devices.size
    a_rows = (1 << process.account_slots_log2) + 1
    t_rows = (1 << process.transfer_slots_log2) + 1
    sh = NamedSharding(mesh, P("shard"))
    sc = NamedSharding(mesh, P())

    def put(x, s):
        return jax.device_put(x, s)

    return {
        "acct_rows": put(jnp.zeros((n, a_rows, ROW_WORDS), dtype=U32), sh),
        "xfer_rows": put(jnp.zeros((n, t_rows, ROW_WORDS), dtype=U32), sh),
        "fulfill": put(jnp.zeros((n, t_rows), dtype=U32), sh),
        "acct_claim": put(jnp.full((n, a_rows), ht.CLAIM_FREE, dtype=U32), sh),
        "xfer_claim": put(jnp.full((n, t_rows), ht.CLAIM_FREE, dtype=U32), sh),
        "bal_acc": put(jnp.zeros((n, a_rows, ROW_WORDS), dtype=U32), sh),
        "commit_ts": put(jnp.uint64(0), sc),
        "acct_count": put(jnp.uint64(0), sc),
        "xfer_count": put(jnp.uint64(0), sc),
    }


class ShardedLedgerKernels:
    """shard_map commit kernels over a 1-D "shard" mesh axis."""

    def __init__(self, mesh: Mesh, process: ConfigProcess):
        self.mesh = mesh
        self.n_shards = mesh.devices.size
        self.process = process
        self.a_log2 = process.account_slots_log2
        self.t_log2 = process.transfer_slots_log2
        self.a_dump = jnp.int32(1 << self.a_log2)
        self.t_dump = jnp.int32(1 << self.t_log2)

        sharded_keys = (
            "acct_rows", "xfer_rows", "fulfill", "acct_claim", "xfer_claim", "bal_acc"
        )
        state_spec = {k: P("shard") for k in sharded_keys}
        state_spec.update({k: P() for k in ("commit_ts", "acct_count", "xfer_count")})

        def wrap(fn, n_out_state=True):
            out_specs = (state_spec, P(), P()) if n_out_state else (P(), P())
            in_specs = (state_spec, P(), P(), P()) if n_out_state else (state_spec, P())
            return jax.jit(
                shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                          check_rep=False),
                donate_argnums=(0,) if n_out_state else (),
            )

        self.commit_transfers = wrap(self._commit_transfers_shard)
        self.commit_accounts = wrap(self._commit_accounts_shard)
        self.lookup_accounts = wrap(self._lookup_accounts_shard, n_out_state=False)
        self.lookup_transfers = wrap(self._lookup_transfers_shard, n_out_state=False)

    # -- sharded lookup: local probe + ownership mask + one row psum --

    def _find(self, rows_local, key4, log2, my_shard):
        own = owner_of_key4(key4, self.n_shards) == my_shard
        slot, found_l = ht.lookup(key4, rows_local, log2)
        mine = own & found_l
        found = jax.lax.psum(mine.astype(U32), "shard") > 0
        row = jax.lax.psum(
            jnp.where(mine[:, None], rows_local[slot], jnp.uint32(0)), "shard"
        )
        return slot, own, mine, found, row

    def _commit_transfers_shard(self, state, ev, n, timestamp):
        my = jax.lax.axis_index("shard")
        acct_rows = state["acct_rows"][0]
        xfer_rows = state["xfer_rows"][0]

        rows_b = ev["rows"]
        B = rows_b.shape[0]
        e = unpack_transfer(rows_b)
        lane = jnp.arange(B, dtype=I32)
        valid = lane < n
        ts_vec = timestamp - n.astype(U64) + lane.astype(U64) + jnp.uint64(1)
        e_a = {**e, "ts": ts_vec}

        dr_k4 = key4_from_fields({"id_lo": e["dr_lo"], "id_hi": e["dr_hi"]})
        cr_k4 = key4_from_fields({"id_lo": e["cr_lo"], "id_hi": e["cr_hi"]})
        dr_slot, _, dr_mine, dr_found, dr_row = self._find(acct_rows, dr_k4, self.a_log2, my)
        cr_slot, _, cr_mine, cr_found, cr_row = self._find(acct_rows, cr_k4, self.a_log2, my)
        _, _, _, ex_found, ex_row = self._find(xfer_rows, rows_b[:, :4], self.t_log2, my)
        dr = unpack_account(dr_row)
        cr = unpack_account(cr_row)
        ex = unpack_transfer(ex_row)

        r0 = jnp.where(e["ts"] != 0, jnp.uint32(3), jnp.uint32(0))
        r0 = validate.transfer_common(e, r0)
        r, amt_lo, amt_hi = validate.validate_simple_transfer(
            r0, e_a, dr, cr, dr_found, cr_found, ex, ex_found
        )
        r = jnp.where(valid, r, jnp.uint32(0))
        ok = valid & (r == 0)

        # Hazards (replicated).
        h_flags = jnp.any(valid & ((e["flags"] & jnp.uint32(_SLOW_FLAGS)) != 0))
        h_dup = _has_duplicate_ids(rows_b[:, :4], valid)
        limit_bits = jnp.uint32(validate.A_DR_LIMIT | validate.A_CR_LIMIT)
        h_limit = jnp.any(ok & (((dr["flags"] | cr["flags"]) & limit_bits) != 0))

        # Local balance-delta accumulation for owned accounts only.
        digits = _amount_digits(amt_lo, amt_hi)
        pending = (e["flags"] & jnp.uint32(F_PENDING)) != 0
        zeros8 = jnp.zeros_like(digits)
        pend8 = jnp.where(pending[:, None], digits, zeros8)
        post8 = jnp.where(pending[:, None], zeros8, digits)
        upd_dr = jnp.concatenate([pend8, post8, zeros8, zeros8], axis=-1)
        upd_cr = jnp.concatenate([zeros8, zeros8, pend8, post8], axis=-1)
        slots_t = jnp.concatenate([
            jnp.where(ok & dr_mine, dr_slot, self.a_dump),
            jnp.where(ok & cr_mine, cr_slot, self.a_dump),
        ])
        upd = jnp.concatenate([upd_dr, upd_cr], axis=0)
        acc = state["bal_acc"][0].at[slots_t].add(upd)
        acc_t = acc[slots_t]
        old_rows_t = acct_rows[slots_t]  # local rows (valid where mine)
        new_rows_t, over_t = _fold_digits(old_rows_t, acc_t)
        over_local = jnp.any(
            (over_t | _combined_overflow(new_rows_t)) & (slots_t != self.a_dump)
        )
        h_overflow = jax.lax.psum(over_local.astype(U32), "shard") > 0
        acc = acc.at[slots_t].set(jnp.zeros_like(upd))
        hazard = h_flags | h_dup | h_limit | h_overflow

        # Apply (predicated on ~hazard so a hazard batch is a no-op and the
        # host can re-route it).
        apply_mask = ok & ~hazard
        slots_t_m = jnp.where(
            jnp.concatenate([apply_mask & dr_mine, apply_mask & cr_mine]),
            jnp.concatenate([dr_slot, cr_slot]),
            self.a_dump,
        )
        acct2 = acct_rows.at[slots_t_m].set(new_rows_t)

        own_id = owner_of_key4(rows_b[:, :4], self.n_shards) == my
        ins = apply_mask & own_id
        ins_rows = _set_ts_words(rows_b, ts_vec)
        slots, xfer2, claim = ht.insert_rows(
            ins_rows, ins, xfer_rows, state["xfer_claim"][0], self.t_log2
        )
        w = jnp.where(ins, slots, self.t_dump)
        fulfill = state["fulfill"][0].at[w].set(jnp.uint32(0))

        any_ok = jnp.any(apply_mask)
        last_ts = jnp.max(jnp.where(apply_mask, ts_vec, jnp.uint64(0)))
        new_state = {
            "acct_rows": acct2[None],
            "xfer_rows": xfer2[None],
            "fulfill": fulfill[None],
            "acct_claim": state["acct_claim"],
            "xfer_claim": claim[None],
            "bal_acc": acc[None],
            "commit_ts": jnp.where(any_ok, last_ts, state["commit_ts"]),
            "acct_count": state["acct_count"],
            "xfer_count": state["xfer_count"] + jnp.sum(apply_mask).astype(U64),
        }
        return new_state, r, hazard

    def _commit_accounts_shard(self, state, ev, n, timestamp):
        my = jax.lax.axis_index("shard")
        acct_rows = state["acct_rows"][0]

        rows_b = ev["rows"]
        B = rows_b.shape[0]
        e = unpack_account(rows_b)
        lane = jnp.arange(B, dtype=I32)
        valid = lane < n
        ts_vec = timestamp - n.astype(U64) + lane.astype(U64) + jnp.uint64(1)

        _, _, _, ex_found, ex_row = self._find(acct_rows, rows_b[:, :4], self.a_log2, my)
        ex = unpack_account(ex_row)
        r0 = jnp.where(e["ts"] != 0, jnp.uint32(3), jnp.uint32(0))
        r = validate.validate_create_account(r0, e, ex, ex_found)
        r = jnp.where(valid, r, jnp.uint32(0))
        ok = valid & (r == 0)

        h_flags = jnp.any(valid & ((e["flags"] & jnp.uint32(validate.A_LINKED)) != 0))
        h_dup = _has_duplicate_ids(rows_b[:, :4], valid)
        hazard = h_flags | h_dup

        own_id = owner_of_key4(rows_b[:, :4], self.n_shards) == my
        ins = ok & ~hazard & own_id
        ins_rows = _set_ts_words(rows_b, ts_vec)
        slots, acct2, claim = ht.insert_rows(
            ins_rows, ins, acct_rows, state["acct_claim"][0], self.a_log2
        )

        apply_mask = ok & ~hazard
        any_ok = jnp.any(apply_mask)
        last_ts = jnp.max(jnp.where(apply_mask, ts_vec, jnp.uint64(0)))
        new_state = {
            "acct_rows": acct2[None],
            "xfer_rows": state["xfer_rows"],
            "fulfill": state["fulfill"],
            "acct_claim": claim[None],
            "xfer_claim": state["xfer_claim"],
            "bal_acc": state["bal_acc"],
            "commit_ts": jnp.where(any_ok, last_ts, state["commit_ts"]),
            "acct_count": state["acct_count"] + jnp.sum(apply_mask).astype(U64),
            "xfer_count": state["xfer_count"],
        }
        return new_state, r, hazard

    def _lookup_accounts_shard(self, state, ids):
        my = jax.lax.axis_index("shard")
        _, _, _, found, row = self._find(state["acct_rows"][0], ids["key4"], self.a_log2, my)
        return found, row

    def _lookup_transfers_shard(self, state, ids):
        my = jax.lax.axis_index("shard")
        _, _, _, found, row = self._find(state["xfer_rows"][0], ids["key4"], self.t_log2, my)
        return found, row


class ShardedLedger:
    """Host wrapper over the sharded kernels (fast-tier batches only; hazard
    batches raise for now — route them to the single-chip serial tier)."""

    def __init__(self, mesh: Mesh, process: ConfigProcess):
        self.mesh = mesh
        self.process = process
        self.kernels = ShardedLedgerKernels(mesh, process)
        self.state = init_sharded_state(mesh, process)

    def execute_dense(self, operation, timestamp: int, events) -> list[int]:
        from tigerbeetle_tpu import types as t

        n = len(events)
        n_pad = _next_pow2(n)
        if operation == Operation.create_transfers:
            arr = events if isinstance(events, np.ndarray) else t.transfers_to_np(events)
            batch = transfers_to_batch(arr, n_pad)
            fn = self.kernels.commit_transfers
        elif operation == Operation.create_accounts:
            arr = events if isinstance(events, np.ndarray) else t.accounts_to_np(events)
            batch = accounts_to_batch(arr, n_pad)
            fn = self.kernels.commit_accounts
        else:
            raise AssertionError(operation)
        new_state, results, hazard = fn(
            self.state, batch, jnp.int32(n), jnp.uint64(timestamp)
        )
        # The old state was donated; the kernel predicates every write on
        # ~hazard so new_state is content-identical to the old on hazard.
        self.state = new_state
        if bool(hazard):
            raise NotImplementedError(
                "hazard batch on the sharded tier: route to the single-chip "
                "serial kernel (sharded serial tier is future work)"
            )
        return [int(x) for x in np.asarray(results)[:n]]
