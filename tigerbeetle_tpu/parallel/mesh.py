"""Multi-chip ledger: the HBM tables sharded over a jax.sharding.Mesh.

The reference scales one replica's state machine only vertically (one core,
one NUMA node — reference: src/vsr/replica.zig single-threaded event loop).
The TPU-native design shards the account and transfer hash tables across
chips of ONE replica over ICI — consensus replication between replicas stays
host-level and is orthogonal (SURVEY.md §5.8).

Layout: wire-row tables of [n_shards, local_rows, 32] u32 sharded on axis 0
over mesh axis "shard". A key's owner shard is a second, independent hash
(owner_of_key4); within the owner it probes that shard's local table with
the same windowed double-hash probes as the single-chip ledger
(ops/hashtable.py). A commit step runs under shard_map:

1. Each shard probes its local tables for ALL lanes, masks hits by
   ownership, and the per-lane 128-byte rows are combined with one psum over
   ICI (exactly one shard contributes non-zero data per found lane).
2. Validation (models/validate.py ladders) is computed replicated — it is
   pure elementwise math over the psum'd rows, identical on every shard.
3. Application is local: each shard updates balances and inserts rows only
   for keys it owns.

Tier selection is HOST-side, exactly like the single-chip ledger
(models/ledger.py HazardTracker): hazard-free batches dispatch the
vectorized kernel; hazard batches (linked chains, post/void, balancing,
duplicate ids, limit accounts, overflow risk) dispatch the sharded SERIAL
kernel — an exact event-at-a-time scan where every store lookup is a
(local probe -> ownership mask -> fused psum) and every write is masked to
the owning shard. Validation and the undo log's replicated fields are
identical on all shards by construction; per-shard undo slots roll back each
shard's own writes on linked-chain breaks.

The fault protocol matches the single-chip ledger: unresolved probes abort
the batch (fast tier: whole-batch no-op + sticky fault; serial tier:
FAULT_SERIAL marks corruption) — the fault word is replicated via psum so
every shard agrees.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8 moved shard_map out of experimental (kwarg: check_vma)
    from jax import shard_map as _shard_map

    def shard_map(fn, **kw):
        return _shard_map(fn, **kw)
except ImportError:  # pragma: no cover — older jax (kwarg: check_rep)
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(fn, **kw):
        kw["check_rep"] = kw.pop("check_vma", True)
        return _shard_map_old(fn, **kw)

from tigerbeetle_tpu.constants import ConfigProcess
from tigerbeetle_tpu.models import validate
from tigerbeetle_tpu.models.ledger import (
    FAULT_CAPACITY,
    FAULT_CLAIM,
    FAULT_OVERFLOW,
    FAULT_PROBE,
    FAULT_SERIAL,
    ROW_WORDS,
    raise_on_fault,
    _TOMB_ROW,
    _amount_digits,
    _combined_overflow,
    _fold_digits,
    _lohi,
    _next_pow2,
    _set_ts_words,
    HazardTracker,
    HostLedgerBase,
    accounts_to_batch,
    build_stored_transfer,
    key4_from_fields,
    pack_account,
    pack_transfer,
    transfers_to_batch,
    unpack_account,
    unpack_transfer,
)
from tigerbeetle_tpu.models.validate import F_LINKED, F_PENDING, F_POST, F_VOID
from tigerbeetle_tpu.ops import hashtable as ht
from tigerbeetle_tpu.ops import u128
from tigerbeetle_tpu.types import Operation

U64 = jnp.uint64
U32 = jnp.uint32
I32 = jnp.int32

# Owner-hash constants — the SINGLE source of truth for both the device hash
# (owner_of_key4) and its host mirror (owner_of_ids_np); a parity test ties
# the two (tests/test_mesh.py). numpy scalars: see ops/hashtable.py note.
_OWNER_MIX = np.uint64(0xD6E8FEB86659FD93)
_OWNER_XOR = np.uint64(0xA5A5A5A5A5A5A5A5)
_OWNER_MUL2 = np.uint64(0x94D049BB133111EB)
_OWNER_SHIFT1 = 29
_OWNER_SHIFT2 = 32


def owner_of_key4(key4, n_shards: int):
    """Owner shard of a key — an independent hash from the slot hash."""
    k = key4.astype(U64)
    lo = k[..., 0] | (k[..., 1] << jnp.uint64(32))
    hi = k[..., 2] | (k[..., 3] << jnp.uint64(32))
    x = (lo ^ _OWNER_XOR) * _OWNER_MIX
    x = x ^ (hi * _OWNER_MIX) ^ (x >> jnp.uint64(_OWNER_SHIFT1))
    x = x * _OWNER_MUL2
    x = x ^ (x >> jnp.uint64(_OWNER_SHIFT2))
    return (x % jnp.uint64(n_shards)).astype(I32)


def owner_of_ids_np(id_lo: np.ndarray, id_hi: np.ndarray, n_shards: int) -> np.ndarray:
    """Host-side mirror of owner_of_key4 (for the per-shard occupancy guard).
    Same constants by construction; parity-tested against the device hash."""
    lo = id_lo.astype(np.uint64)
    hi = id_hi.astype(np.uint64)
    with np.errstate(over="ignore"):
        x = (lo ^ _OWNER_XOR) * _OWNER_MIX
        x = x ^ (hi * _OWNER_MIX) ^ (x >> np.uint64(_OWNER_SHIFT1))
        x = x * _OWNER_MUL2
        x = x ^ (x >> np.uint64(_OWNER_SHIFT2))
    return (x % np.uint64(n_shards)).astype(np.int64)


def init_sharded_state(mesh: Mesh, process: ConfigProcess) -> dict:
    """Tables of [n_shards, local_rows, 32] sharded over mesh axis "shard".
    local capacity = 2^account_slots_log2 etc. PER SHARD."""
    n = mesh.devices.size
    a_rows = (1 << process.account_slots_log2) + 1
    t_rows = (1 << process.transfer_slots_log2) + 1
    sh = NamedSharding(mesh, P("shard"))
    sc = NamedSharding(mesh, P())

    def put(x, s):
        return jax.device_put(x, s)

    return {
        "acct_rows": put(jnp.zeros((n, a_rows, ROW_WORDS), dtype=U32), sh),
        "xfer_rows": put(jnp.zeros((n, t_rows, ROW_WORDS), dtype=U32), sh),
        "fulfill": put(jnp.zeros((n, t_rows), dtype=U32), sh),
        "acct_claim": put(jnp.full((n, a_rows), ht.CLAIM_FREE, dtype=U32), sh),
        "xfer_claim": put(jnp.full((n, t_rows), ht.CLAIM_FREE, dtype=U32), sh),
        "bal_acc": put(jnp.zeros((n, a_rows, ROW_WORDS), dtype=U32), sh),
        # per-shard ever-applied insert counters (device load guard)
        "acct_used_slots": put(jnp.zeros((n,), dtype=jnp.uint64), sh),
        "xfer_used_slots": put(jnp.zeros((n,), dtype=jnp.uint64), sh),
        "commit_ts": put(jnp.uint64(0), sc),
        "acct_count": put(jnp.uint64(0), sc),
        "xfer_count": put(jnp.uint64(0), sc),
        "fault": put(jnp.uint32(0), sc),
    }


class ShardedLedgerKernels:
    """shard_map commit kernels over a 1-D "shard" mesh axis. Mode ("fast" /
    "serial") is selected by the HOST per batch — both kernels are
    straight-line programs (the serial one a lax.scan), no on-device
    dispatch."""

    def __init__(self, mesh: Mesh, process: ConfigProcess):
        self.mesh = mesh
        self.n_shards = mesh.devices.size
        self.process = process
        self.a_log2 = process.account_slots_log2
        self.t_log2 = process.transfer_slots_log2
        # Python ints (embedded as literals) — capturing jnp scalars in the
        # kernels would poison dispatch (see ops/hashtable.py note).
        self.a_dump = 1 << self.a_log2
        self.t_dump = 1 << self.t_log2

        sharded_keys = (
            "acct_rows", "xfer_rows", "fulfill", "acct_claim", "xfer_claim",
            "bal_acc", "acct_used_slots", "xfer_used_slots",
        )
        state_spec = {k: P("shard") for k in sharded_keys}
        state_spec.update(
            {k: P() for k in ("commit_ts", "acct_count", "xfer_count", "fault")}
        )

        def wrap(fn, out_state=True):
            out_specs = (state_spec, P()) if out_state else (P(), P(), P())
            in_specs = (state_spec, P(), P(), P()) if out_state else (state_spec, P())
            return jax.jit(
                shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                          check_vma=False),
                donate_argnums=(0,) if out_state else (),
            )

        self.commit_transfers_fast = wrap(self._commit_transfers_fast)
        self.commit_transfers_serial = wrap(self._commit_transfers_serial)
        self.commit_accounts_fast = wrap(self._commit_accounts_fast)
        self.commit_accounts_serial = wrap(self._commit_accounts_serial)
        self.lookup_accounts = wrap(self._lookup_accounts_shard, out_state=False)
        self.lookup_transfers = wrap(self._lookup_transfers_shard, out_state=False)

    # ------------------------------------------------------------------
    # sharded lookup: local probe + ownership mask + one fused psum
    # ------------------------------------------------------------------

    def _find(self, rows_local, key4, log2, my_shard, window=ht.WINDOW):
        """Batched sharded probe. Returns (slot local-i32, mine bool,
        found bool, row [.., 32], resolved bool) — found/row/resolved are
        replicated (psum'd); slot/mine are local."""
        own = owner_of_key4(key4, self.n_shards) == my_shard
        slot, found_l, res_l = ht.lookup(key4, rows_local, log2, window=window)
        mine = own & found_l
        # Owner shards must resolve their probes; non-owners don't matter.
        bad_local = own & ~res_l
        row_c = jnp.where(mine[..., None], rows_local[slot], jnp.uint32(0))
        found_c, bad_c, row = jax.lax.psum(
            (mine.astype(U32), bad_local.astype(U32), row_c), "shard"
        )
        return slot, mine, found_c > 0, row, bad_c == 0

    # ------------------------------------------------------------------
    # fast tier
    # ------------------------------------------------------------------

    def _commit_transfers_fast(self, state, ev, n, timestamp):
        my = jax.lax.axis_index("shard")
        acct_rows = state["acct_rows"][0]
        xfer_rows = state["xfer_rows"][0]

        rows_b = ev["rows"]
        B = rows_b.shape[0]
        e = unpack_transfer(rows_b)
        lane = jnp.arange(B, dtype=I32)
        valid = lane < n
        ts_vec = timestamp - n.astype(U64) + lane.astype(U64) + jnp.uint64(1)
        e_a = {**e, "ts": ts_vec}

        dr_k4 = key4_from_fields({"id_lo": e["dr_lo"], "id_hi": e["dr_hi"]})
        cr_k4 = key4_from_fields({"id_lo": e["cr_lo"], "id_hi": e["cr_hi"]})
        both_k4 = jnp.concatenate([dr_k4, cr_k4], axis=0)
        b_slot, b_mine, b_found, b_row, b_res = self._find(
            acct_rows, both_k4, self.a_log2, my
        )
        dr_slot, cr_slot = b_slot[:B], b_slot[B:]
        dr_mine, cr_mine = b_mine[:B], b_mine[B:]
        dr_found, cr_found = b_found[:B], b_found[B:]
        dr_row, cr_row = b_row[:B], b_row[B:]
        _, _, ex_found, ex_row, ex_res = self._find(
            xfer_rows, rows_b[:, :4], self.t_log2, my
        )
        dr = unpack_account(dr_row)
        cr = unpack_account(cr_row)
        ex = unpack_transfer(ex_row)

        r0 = jnp.where(e["ts"] != 0, jnp.uint32(3), jnp.uint32(0))
        r0 = validate.transfer_common(e, r0)
        r, amt_lo, amt_hi = validate.validate_simple_transfer(
            r0, e_a, dr, cr, dr_found, cr_found, ex, ex_found
        )
        r = jnp.where(valid, r, jnp.uint32(0))
        ok = valid & (r == 0)

        valid2 = jnp.concatenate([valid, valid])
        probe_bad = jnp.any(valid2 & ~b_res) | jnp.any(valid & ~ex_res)

        # Claim insert slots on the id's owner shard (pure claim phase).
        own_id = owner_of_key4(rows_b[:, :4], self.n_shards) == my
        ins = ok & own_id
        ins_slots, claim, ins_res = ht.claim_slots(
            rows_b[:, :4], ins, xfer_rows, state["xfer_claim"][0], self.t_log2
        )
        claim_bad_l = jnp.any(~ins_res)

        # Local balance-delta accumulation for owned accounts only.
        digits = _amount_digits(amt_lo, amt_hi)
        pending = (e["flags"] & jnp.uint32(F_PENDING)) != 0
        zeros8 = jnp.zeros_like(digits)
        pend8 = jnp.where(pending[:, None], digits, zeros8)
        post8 = jnp.where(pending[:, None], zeros8, digits)
        upd_dr = jnp.concatenate([pend8, post8, zeros8, zeros8], axis=-1)
        upd_cr = jnp.concatenate([zeros8, zeros8, pend8, post8], axis=-1)
        slots_t = jnp.concatenate([
            jnp.where(ok & dr_mine, dr_slot, self.a_dump),
            jnp.where(ok & cr_mine, cr_slot, self.a_dump),
        ])
        upd = jnp.concatenate([upd_dr, upd_cr], axis=0)
        acc = state["bal_acc"][0].at[slots_t].add(upd)
        acc_t = acc[slots_t]
        old_rows_t = acct_rows[slots_t]  # local rows (valid where mine)
        new_rows_t, over_t = _fold_digits(old_rows_t, acc_t)
        over_bad_l = jnp.any(
            (over_t | _combined_overflow(new_rows_t)) & (slots_t != self.a_dump)
        )
        acc = acc.at[slots_t].set(jnp.zeros_like(upd))

        # per-shard device load guard over owned inserts
        ins_n = jnp.sum(ins).astype(jnp.uint64)
        cap_bad_l = state["xfer_used_slots"][0] + ins_n > np.uint64(
            self.t_dump // 2
        )
        claim_bad, over_bad, cap_bad = jax.lax.psum(
            (claim_bad_l.astype(U32), over_bad_l.astype(U32),
             cap_bad_l.astype(U32)), "shard"
        )
        fault = (
            state["fault"]
            | jnp.where(probe_bad, jnp.uint32(FAULT_PROBE), jnp.uint32(0))
            | jnp.where(claim_bad > 0, jnp.uint32(FAULT_CLAIM), jnp.uint32(0))
            | jnp.where(over_bad > 0, jnp.uint32(FAULT_OVERFLOW), jnp.uint32(0))
            | jnp.where(cap_bad > 0, jnp.uint32(FAULT_CAPACITY), jnp.uint32(0))
        )
        proceed = fault == 0

        # --- application (gated on proceed) ---
        acct2 = acct_rows.at[jnp.where(proceed, slots_t, self.a_dump)].set(new_rows_t)
        ins_rows = _set_ts_words(rows_b, ts_vec)
        w = jnp.where(proceed & ins, ins_slots, self.t_dump)
        xfer2 = xfer_rows.at[w].set(ins_rows)
        fulfill = state["fulfill"][0].at[w].set(jnp.uint32(0))

        applied = proceed & jnp.any(ok)
        last_ts = jnp.max(jnp.where(ok, ts_vec, jnp.uint64(0)))
        new_state = {
            "acct_rows": acct2[None],
            "xfer_rows": xfer2[None],
            "fulfill": fulfill[None],
            "acct_claim": state["acct_claim"],
            "xfer_claim": claim[None],
            "bal_acc": acc[None],
            "acct_used_slots": state["acct_used_slots"],
            "xfer_used_slots": state["xfer_used_slots"]
            + jnp.where(proceed, ins_n, jnp.uint64(0))[None],
            "commit_ts": jnp.where(applied, last_ts, state["commit_ts"]),
            "acct_count": state["acct_count"],
            "xfer_count": state["xfer_count"]
            + jnp.where(proceed, jnp.sum(ok).astype(U64), jnp.uint64(0)),
            "fault": fault,
        }
        return new_state, r

    def _commit_accounts_fast(self, state, ev, n, timestamp):
        my = jax.lax.axis_index("shard")
        acct_rows = state["acct_rows"][0]

        rows_b = ev["rows"]
        B = rows_b.shape[0]
        e = unpack_account(rows_b)
        lane = jnp.arange(B, dtype=I32)
        valid = lane < n
        ts_vec = timestamp - n.astype(U64) + lane.astype(U64) + jnp.uint64(1)

        _, _, ex_found, ex_row, ex_res = self._find(
            acct_rows, rows_b[:, :4], self.a_log2, my
        )
        ex = unpack_account(ex_row)
        r0 = jnp.where(e["ts"] != 0, jnp.uint32(3), jnp.uint32(0))
        r = validate.validate_create_account(r0, e, ex, ex_found)
        r = jnp.where(valid, r, jnp.uint32(0))
        ok = valid & (r == 0)

        probe_bad = jnp.any(valid & ~ex_res)
        own_id = owner_of_key4(rows_b[:, :4], self.n_shards) == my
        ins = ok & own_id
        ins_slots, claim, ins_res = ht.claim_slots(
            rows_b[:, :4], ins, acct_rows, state["acct_claim"][0], self.a_log2
        )
        ins_n = jnp.sum(ins).astype(jnp.uint64)
        cap_bad_l = state["acct_used_slots"][0] + ins_n > np.uint64(
            self.a_dump // 2
        )
        claim_bad_c, cap_bad_c = jax.lax.psum(
            (jnp.any(~ins_res).astype(U32), cap_bad_l.astype(U32)), "shard"
        )

        fault = (
            state["fault"]
            | jnp.where(probe_bad, jnp.uint32(FAULT_PROBE), jnp.uint32(0))
            | jnp.where(claim_bad_c > 0, jnp.uint32(FAULT_CLAIM), jnp.uint32(0))
            | jnp.where(cap_bad_c > 0, jnp.uint32(FAULT_CAPACITY), jnp.uint32(0))
        )
        proceed = fault == 0

        ins_rows = _set_ts_words(rows_b, ts_vec)
        w = jnp.where(proceed & ins, ins_slots, self.a_dump)
        acct2 = acct_rows.at[w].set(ins_rows)

        applied = proceed & jnp.any(ok)
        last_ts = jnp.max(jnp.where(ok, ts_vec, jnp.uint64(0)))
        new_state = {
            "acct_rows": acct2[None],
            "xfer_rows": state["xfer_rows"],
            "fulfill": state["fulfill"],
            "acct_claim": claim[None],
            "xfer_claim": state["xfer_claim"],
            "bal_acc": state["bal_acc"],
            "acct_used_slots": state["acct_used_slots"]
            + jnp.where(proceed, ins_n, jnp.uint64(0))[None],
            "xfer_used_slots": state["xfer_used_slots"],
            "commit_ts": jnp.where(applied, last_ts, state["commit_ts"]),
            "acct_count": state["acct_count"]
            + jnp.where(proceed, jnp.sum(ok).astype(U64), jnp.uint64(0)),
            "xfer_count": state["xfer_count"],
            "fault": fault,
        }
        return new_state, r

    # ------------------------------------------------------------------
    # serial tier (exact; hazard batches)
    # ------------------------------------------------------------------

    def _find1(self, rows_local, fulfill_local, keys, log2, my):
        """Fused scalar-step probe of k stacked keys [k, 4]. Returns
        (slot [k] local, mine [k] local, found [k] repl, rows [k, 32] repl,
        fulfill [k] repl, bad repl-bool)."""
        own = owner_of_key4(keys, self.n_shards) == my
        slot, found_l, res_l = ht.lookup(
            keys, rows_local, log2, window=ht.WINDOW_SCALAR
        )
        mine = own & found_l
        bad_l = jnp.any(own & ~res_l)
        row_c = jnp.where(mine[:, None], rows_local[slot], jnp.uint32(0))
        ful_c = (
            jnp.where(mine, fulfill_local[slot], jnp.uint32(0))
            if fulfill_local is not None
            else jnp.zeros(keys.shape[0], dtype=U32)
        )
        found_c, row, ful, bad_c = jax.lax.psum(
            (mine.astype(U32), row_c, ful_c, bad_l.astype(U32)), "shard"
        )
        return slot, mine, found_c > 0, row, ful, bad_c > 0

    def _commit_transfers_serial(self, state, ev, n, timestamp):
        my = jax.lax.axis_index("shard")
        rows_b = ev["rows"]
        B = rows_b.shape[0]
        lanes = jnp.arange(B, dtype=I32)
        a_dump, t_dump = self.a_dump, self.t_dump
        tomb_row = _TOMB_ROW  # numpy: embeds as a literal
        # entry gates: sticky fault + per-shard device load guard
        # (conservative: all n events charged against every shard)
        cap_bad_l = state["xfer_used_slots"][0] + n.astype(U64) > np.uint64(
            self.t_dump // 2
        )
        cap_bad = jax.lax.psum(cap_bad_l.astype(U32), "shard") > 0
        fault0 = state["fault"] | jnp.where(
            cap_bad, jnp.uint32(FAULT_CAPACITY), jnp.uint32(0)
        )
        n = jnp.where(fault0 == 0, n, jnp.int32(0))

        undo0 = {
            "kind": jnp.zeros(B, dtype=U32),
            "dr_mine": jnp.zeros(B, dtype=bool),
            "cr_mine": jnp.zeros(B, dtype=bool),
            "t_mine": jnp.zeros(B, dtype=bool),
            "p_mine": jnp.zeros(B, dtype=bool),
            "dr_slot": jnp.zeros(B, dtype=I32),
            "cr_slot": jnp.zeros(B, dtype=I32),
            "t_slot": jnp.zeros(B, dtype=I32),
            "p_slot": jnp.zeros(B, dtype=I32),
            "a_lo": jnp.zeros(B, dtype=U64),
            "a_hi": jnp.zeros(B, dtype=U64),
            "pa_lo": jnp.zeros(B, dtype=U64),
            "pa_hi": jnp.zeros(B, dtype=U64),
        }
        carry0 = (
            state["acct_rows"][0], state["xfer_rows"][0], state["fulfill"][0],
            jnp.zeros(B, dtype=U32),  # results (replicated)
            undo0,
            jnp.int32(-1),  # chain_start (replicated)
            jnp.zeros((), dtype=bool),  # chain_broken (replicated)
            state["commit_ts"],
            jnp.zeros((), dtype=bool),  # unresolved accumulator (replicated)
        )

        def step(carry, x):
            (acct_rows, xfer_rows, fulfill, results, undo, chain_start,
             chain_broken, commit_ts, probe_bad) = carry
            i, row_e = x
            e = unpack_transfer(row_e)
            active = i < n
            linked = active & ((e["flags"] & jnp.uint32(F_LINKED)) != 0)

            opening = linked & (chain_start < 0)
            chain_start = jnp.where(opening, i, chain_start)
            in_chain = chain_start >= 0
            is_last = i == (n - 1)

            ts = timestamp - n.astype(U64) + i.astype(U64) + jnp.uint64(1)
            e_a = {**e, "ts": ts}

            lad = validate.Ladder(jnp.uint32(0))
            lad.set(in_chain & is_last & linked, 2)  # linked_event_chain_open
            lad.set(active & chain_broken, 1)  # linked_event_failed
            lad.set(e["ts"] != 0, 3)  # timestamp_must_be_zero
            r0 = validate.transfer_common(e, lad.r)

            k4 = key4_from_fields
            # Fused probes: accounts (dr, cr) and transfers (ex, p).
            a_keys = jnp.stack([
                k4({"id_lo": e["dr_lo"], "id_hi": e["dr_hi"]}),
                k4({"id_lo": e["cr_lo"], "id_hi": e["cr_hi"]}),
            ])
            a_slot, a_mine, a_found, a_rows_g, _, bad_a = self._find1(
                acct_rows, None, a_keys, self.a_log2, my
            )
            t_keys = jnp.stack([
                row_e[:4],
                k4({"id_lo": e["pid_lo"], "id_hi": e["pid_hi"]}),
            ])
            t_slot, t_mine, t_found, t_rows_g, t_ful, bad_t = self._find1(
                xfer_rows, fulfill, t_keys, self.t_log2, my
            )
            dr = unpack_account(a_rows_g[0])
            cr = unpack_account(a_rows_g[1])
            dr_found, cr_found = a_found[0], a_found[1]
            ex = unpack_transfer(t_rows_g[0])
            p = unpack_transfer(t_rows_g[1])
            ex_found, p_found = t_found[0], t_found[1]
            p["fulfill"] = t_ful[1]
            # The pending transfer's accounts (post/void path); garbage rows
            # when ~p_found, gated by the validator.
            pa_keys = jnp.stack([
                k4({"id_lo": p["dr_lo"], "id_hi": p["dr_hi"]}),
                k4({"id_lo": p["cr_lo"], "id_hi": p["cr_hi"]}),
            ])
            pa_slot, pa_mine, _, pa_rows_g, _, bad_pa = self._find1(
                acct_rows, None, pa_keys, self.a_log2, my
            )
            pdr = unpack_account(pa_rows_g[0])
            pcr = unpack_account(pa_rows_g[1])
            probe_bad = probe_bad | (active & (bad_a | bad_t | bad_pa))

            is_pv = (e["flags"] & jnp.uint32(F_POST | F_VOID)) != 0
            r_s, amt_s_lo, amt_s_hi = validate.validate_simple_transfer(
                r0, e_a, dr, cr, dr_found, cr_found, ex, ex_found
            )
            r_pv, amt_pv_lo, amt_pv_hi = validate.validate_post_void(
                r0, e_a, p, p_found, ex, ex_found
            )
            r = jnp.where(is_pv, r_pv, r_s)
            r = jnp.where(active, r, jnp.uint32(0))
            ok = active & (r == 0)

            amt_lo = jnp.where(is_pv, amt_pv_lo, amt_s_lo)
            amt_hi = jnp.where(is_pv, amt_pv_hi, amt_s_hi)
            is_post = is_pv & ((e["flags"] & jnp.uint32(F_POST)) != 0)
            is_pending = ~is_pv & ((e["flags"] & jnp.uint32(F_PENDING)) != 0)

            # --- build the row to insert (replicated; shared helper) ---
            ins_row = pack_transfer(
                build_stored_transfer(e, p, is_pv, amt_lo, amt_hi, ts)
            )
            # Insert on the id's owner shard only.
            id_own = owner_of_key4(row_e[:4], self.n_shards) == my
            free_slot, free_ok = ht.probe_free(row_e[:4], xfer_rows, self.t_log2)
            probe_bad = probe_bad | jnp.any(
                jax.lax.psum((ok & id_own & ~free_ok).astype(U32), "shard") > 0
            )
            t_write = ok & id_own & free_ok
            w = jnp.where(t_write, free_slot, t_dump)
            xfer_rows = xfer_rows.at[w].set(ins_row)
            fulfill = fulfill.at[w].set(jnp.uint32(0))
            # fulfill update at the pending transfer (p's owner shard).
            p_mine_l = t_mine[1]
            fw = jnp.where(ok & is_pv & p_mine_l, t_slot[1], t_dump)
            fulfill = fulfill.at[fw].set(
                jnp.where(is_post, jnp.uint32(1), jnp.uint32(2))
            )

            # --- balance application (masked to owning shards) ---
            tgt_dr_mine = jnp.where(is_pv, pa_mine[0], a_mine[0])
            tgt_cr_mine = jnp.where(is_pv, pa_mine[1], a_mine[1])
            tgt_dr_slot = jnp.where(is_pv, pa_slot[0], a_slot[0])
            tgt_cr_slot = jnp.where(is_pv, pa_slot[1], a_slot[1])
            tdr = {k: jnp.where(is_pv, pdr[k], dr[k]) for k in dr}
            tcr = {k: jnp.where(is_pv, pcr[k], cr[k]) for k in cr}

            def upd(row_d, bal, add_cond, add_lo, add_hi, sub_cond, sub_lo, sub_hi):
                lo, hi = row_d[bal + "_lo"], row_d[bal + "_hi"]
                a_lo2, a_hi2, _ = u128.add(lo, hi, add_lo, add_hi)
                lo = jnp.where(add_cond, a_lo2, lo)
                hi = jnp.where(add_cond, a_hi2, hi)
                s_lo2, s_hi2, _ = u128.sub(lo, hi, sub_lo, sub_hi)
                lo = jnp.where(sub_cond, s_lo2, lo)
                hi = jnp.where(sub_cond, s_hi2, hi)
                return lo, hi

            false_ = jnp.zeros((), dtype=bool)
            zero64 = jnp.uint64(0)
            dpo_add = (~is_pv & ~is_pending) | is_post
            tdr["dp_lo"], tdr["dp_hi"] = upd(
                tdr, "dp", is_pending, amt_lo, amt_hi, is_pv, p["amt_lo"], p["amt_hi"]
            )
            tdr["dpo_lo"], tdr["dpo_hi"] = upd(
                tdr, "dpo", dpo_add, amt_lo, amt_hi, false_, zero64, zero64
            )
            tcr["cp_lo"], tcr["cp_hi"] = upd(
                tcr, "cp", is_pending, amt_lo, amt_hi, is_pv, p["amt_lo"], p["amt_hi"]
            )
            tcr["cpo_lo"], tcr["cpo_hi"] = upd(
                tcr, "cpo", dpo_add, amt_lo, amt_hi, false_, zero64, zero64
            )
            dw = jnp.where(ok & tgt_dr_mine, tgt_dr_slot, a_dump)
            cw = jnp.where(ok & tgt_cr_mine, tgt_cr_slot, a_dump)
            acct_rows = acct_rows.at[dw].set(pack_account(tdr))
            acct_rows = acct_rows.at[cw].set(pack_account(tcr))
            commit_ts = jnp.where(ok, ts, commit_ts)

            # --- undo log entry (kinds/amounts replicated; slots local) ---
            kind = jnp.where(
                ~ok,
                jnp.uint32(0),
                jnp.where(
                    is_pv,
                    jnp.where(is_post, jnp.uint32(3), jnp.uint32(4)),
                    jnp.where(is_pending, jnp.uint32(2), jnp.uint32(1)),
                ),
            )
            undo = {
                "kind": undo["kind"].at[i].set(kind),
                "dr_mine": undo["dr_mine"].at[i].set(tgt_dr_mine),
                "cr_mine": undo["cr_mine"].at[i].set(tgt_cr_mine),
                "t_mine": undo["t_mine"].at[i].set(id_own),
                "p_mine": undo["p_mine"].at[i].set(p_mine_l),
                "dr_slot": undo["dr_slot"].at[i].set(tgt_dr_slot),
                "cr_slot": undo["cr_slot"].at[i].set(tgt_cr_slot),
                "t_slot": undo["t_slot"].at[i].set(free_slot),
                "p_slot": undo["p_slot"].at[i].set(t_slot[1]),
                "a_lo": undo["a_lo"].at[i].set(amt_lo),
                "a_hi": undo["a_hi"].at[i].set(amt_hi),
                "pa_lo": undo["pa_lo"].at[i].set(p["amt_lo"]),
                "pa_hi": undo["pa_hi"].at[i].set(p["amt_hi"]),
            }

            # --- chain break: roll back [chain_start, i) ---
            break_now = active & (r != 0) & in_chain & ~chain_broken
            lo_k = jnp.where(break_now, chain_start, i)

            def undo_body(k, tabs):
                acct_rows, xfer_rows, fulfill = tabs
                kd = undo["kind"][k]
                applied_k = kd != 0
                k1, k2 = kd == 1, kd == 2
                k3, k4_ = kd == 3, kd == 4
                ua_lo, ua_hi = undo["a_lo"][k], undo["a_hi"][k]
                up_lo, up_hi = undo["pa_lo"][k], undo["pa_hi"][k]
                add_p = k3 | k4_
                sub_pend = k2
                sub_post = k1 | k3

                def inv(fields, bal, addc, subc, s_lo, s_hi):
                    lo, hi = fields[bal + "_lo"], fields[bal + "_hi"]
                    a_lo2, a_hi2, _ = u128.add(lo, hi, up_lo, up_hi)
                    lo = jnp.where(addc, a_lo2, lo)
                    hi = jnp.where(addc, a_hi2, hi)
                    s_lo2, s_hi2, _ = u128.sub(lo, hi, s_lo, s_hi)
                    lo = jnp.where(subc, s_lo2, lo)
                    hi = jnp.where(subc, s_hi2, hi)
                    return lo, hi

                dwk = jnp.where(
                    applied_k & undo["dr_mine"][k], undo["dr_slot"][k], a_dump
                )
                cwk = jnp.where(
                    applied_k & undo["cr_mine"][k], undo["cr_slot"][k], a_dump
                )
                fdr = unpack_account(acct_rows[dwk])
                fcr = unpack_account(acct_rows[cwk])
                fdr["dp_lo"], fdr["dp_hi"] = inv(fdr, "dp", add_p, sub_pend, ua_lo, ua_hi)
                fdr["dpo_lo"], fdr["dpo_hi"] = inv(fdr, "dpo", false_, sub_post, ua_lo, ua_hi)
                fcr["cp_lo"], fcr["cp_hi"] = inv(fcr, "cp", add_p, sub_pend, ua_lo, ua_hi)
                fcr["cpo_lo"], fcr["cpo_hi"] = inv(fcr, "cpo", false_, sub_post, ua_lo, ua_hi)
                acct_rows = acct_rows.at[dwk].set(pack_account(fdr))
                acct_rows = acct_rows.at[cwk].set(pack_account(fcr))
                twk = jnp.where(
                    applied_k & undo["t_mine"][k], undo["t_slot"][k], t_dump
                )
                xfer_rows = xfer_rows.at[twk].set(tomb_row)
                fwk = jnp.where(
                    (k3 | k4_) & undo["p_mine"][k], undo["p_slot"][k], t_dump
                )
                fulfill = fulfill.at[fwk].set(jnp.uint32(0))
                return acct_rows, xfer_rows, fulfill

            acct_rows, xfer_rows, fulfill = jax.lax.fori_loop(
                lo_k, i, undo_body, (acct_rows, xfer_rows, fulfill)
            )

            results = jnp.where(
                break_now & (lanes >= chain_start) & (lanes < i), jnp.uint32(1), results
            )
            results = results.at[i].set(r)
            chain_broken = chain_broken | break_now
            chain_end = in_chain & (~linked | (r == 2))
            chain_start = jnp.where(chain_end, jnp.int32(-1), chain_start)
            chain_broken = jnp.where(chain_end, False, chain_broken)

            return (
                acct_rows, xfer_rows, fulfill, results, undo,
                chain_start, chain_broken, commit_ts, probe_bad,
            ), None

        (acct_rows, xfer_rows, fulfill, results, undo, _, _, commit_ts,
         probe_bad), _ = jax.lax.scan(step, carry0, (lanes, rows_b))
        ok_n = jnp.sum((results == 0) & (lanes < n)).astype(U64)
        applied_l = jnp.sum(((undo["kind"] != 0) & undo["t_mine"]).astype(U64))
        new_state = {
            "acct_rows": acct_rows[None],
            "xfer_rows": xfer_rows[None],
            "fulfill": fulfill[None],
            "acct_claim": state["acct_claim"],
            "xfer_claim": state["xfer_claim"],
            "bal_acc": state["bal_acc"],
            "acct_used_slots": state["acct_used_slots"],
            "xfer_used_slots": state["xfer_used_slots"] + applied_l[None],
            "commit_ts": commit_ts,
            "acct_count": state["acct_count"],
            "xfer_count": state["xfer_count"] + ok_n,
            "fault": fault0
            | jnp.where(probe_bad, jnp.uint32(FAULT_SERIAL), jnp.uint32(0)),
        }
        return new_state, results

    def _commit_accounts_serial(self, state, ev, n, timestamp):
        my = jax.lax.axis_index("shard")
        rows_b = ev["rows"]
        B = rows_b.shape[0]
        lanes = jnp.arange(B, dtype=I32)
        a_dump = self.a_dump
        tomb_row = _TOMB_ROW  # numpy: embeds as a literal
        cap_bad_l = state["acct_used_slots"][0] + n.astype(U64) > np.uint64(
            self.a_dump // 2
        )
        cap_bad = jax.lax.psum(cap_bad_l.astype(U32), "shard") > 0
        fault0 = state["fault"] | jnp.where(
            cap_bad, jnp.uint32(FAULT_CAPACITY), jnp.uint32(0)
        )
        n = jnp.where(fault0 == 0, n, jnp.int32(0))

        undo0 = {
            "slot": jnp.zeros(B, dtype=I32),
            "kind": jnp.zeros(B, dtype=U32),
            "mine": jnp.zeros(B, dtype=bool),
        }
        carry0 = (
            state["acct_rows"][0],
            jnp.zeros(B, dtype=U32),
            undo0,
            jnp.int32(-1),
            jnp.zeros((), dtype=bool),
            state["commit_ts"],
            jnp.zeros((), dtype=bool),
        )

        def step(carry, x):
            (acct_rows, results, undo, chain_start, chain_broken, commit_ts,
             probe_bad) = carry
            i, row_e = x
            e = unpack_account(row_e)
            active = i < n
            linked = active & ((e["flags"] & jnp.uint32(validate.A_LINKED)) != 0)
            opening = linked & (chain_start < 0)
            chain_start = jnp.where(opening, i, chain_start)
            in_chain = chain_start >= 0
            is_last = i == (n - 1)
            ts = timestamp - n.astype(U64) + i.astype(U64) + jnp.uint64(1)

            lad = validate.Ladder(jnp.uint32(0))
            lad.set(in_chain & is_last & linked, 2)
            lad.set(active & chain_broken, 1)
            lad.set(e["ts"] != 0, 3)

            _, _, ex_found_v, ex_row, _, bad = self._find1(
                acct_rows, None, row_e[None, :4], self.a_log2, my
            )
            ex = unpack_account(ex_row[0])
            r = validate.validate_create_account(lad.r, e, ex, ex_found_v[0])
            r = jnp.where(active, r, jnp.uint32(0))
            ok = active & (r == 0)

            id_own = owner_of_key4(row_e[:4], self.n_shards) == my
            free_slot, free_ok = ht.probe_free(row_e[:4], acct_rows, self.a_log2)
            probe_bad = probe_bad | (active & bad) | jnp.any(
                jax.lax.psum((ok & id_own & ~free_ok).astype(U32), "shard") > 0
            )
            do_write = ok & id_own & free_ok
            w = jnp.where(do_write, free_slot, a_dump)
            t0, t1 = _lohi(ts)
            ins_row = jnp.concatenate([row_e[:30], t0[None], t1[None]])
            acct_rows = acct_rows.at[w].set(ins_row)
            commit_ts = jnp.where(ok, ts, commit_ts)

            undo = {
                "kind": undo["kind"].at[i].set(jnp.where(ok, jnp.uint32(5), jnp.uint32(0))),
                "slot": undo["slot"].at[i].set(free_slot),
                "mine": undo["mine"].at[i].set(id_own),
            }

            break_now = active & (r != 0) & in_chain & ~chain_broken
            lo_k = jnp.where(break_now, chain_start, i)

            def undo_body(k, acct_rows):
                applied_k = (undo["kind"][k] != 0) & undo["mine"][k]
                sl = jnp.where(applied_k, undo["slot"][k], a_dump)
                return acct_rows.at[sl].set(tomb_row)

            acct_rows = jax.lax.fori_loop(lo_k, i, undo_body, acct_rows)
            results = jnp.where(
                break_now & (lanes >= chain_start) & (lanes < i), jnp.uint32(1), results
            )
            results = results.at[i].set(r)
            chain_broken = chain_broken | break_now
            chain_end = in_chain & (~linked | (r == 2))
            chain_start = jnp.where(chain_end, jnp.int32(-1), chain_start)
            chain_broken = jnp.where(chain_end, False, chain_broken)
            return (acct_rows, results, undo, chain_start, chain_broken,
                    commit_ts, probe_bad), None

        (acct_rows, results, undo, _, _, commit_ts, probe_bad), _ = jax.lax.scan(
            step, carry0, (lanes, rows_b)
        )
        ok_n = jnp.sum((results == 0) & (lanes < n)).astype(U64)
        applied_l = jnp.sum(((undo["kind"] != 0) & undo["mine"]).astype(U64))
        new_state = {
            "acct_rows": acct_rows[None],
            "xfer_rows": state["xfer_rows"],
            "fulfill": state["fulfill"],
            "acct_claim": state["acct_claim"],
            "xfer_claim": state["xfer_claim"],
            "bal_acc": state["bal_acc"],
            "acct_used_slots": state["acct_used_slots"] + applied_l[None],
            "xfer_used_slots": state["xfer_used_slots"],
            "commit_ts": commit_ts,
            "acct_count": state["acct_count"] + ok_n,
            "xfer_count": state["xfer_count"],
            "fault": fault0
            | jnp.where(probe_bad, jnp.uint32(FAULT_SERIAL), jnp.uint32(0)),
        }
        return new_state, results

    # ------------------------------------------------------------------
    # lookups
    # ------------------------------------------------------------------

    def _lookup_accounts_shard(self, state, ids):
        my = jax.lax.axis_index("shard")
        _, _, found, row, res = self._find(
            state["acct_rows"][0], ids["key4"], self.a_log2, my
        )
        return found, row, res

    def _lookup_transfers_shard(self, state, ids):
        my = jax.lax.axis_index("shard")
        _, _, found, row, res = self._find(
            state["xfer_rows"][0], ids["key4"], self.t_log2, my
        )
        return found, row, res


class ShardedLedger(HostLedgerBase):
    """Host wrapper over the sharded kernels. Mirrors DeviceLedger's
    execute() API (HostLedgerBase: prepare/lookups); tier selection is the
    same host-side HazardTracker."""

    def __init__(self, mesh: Mesh, process: ConfigProcess, mode: str = "auto"):
        self.mesh = mesh
        self.process = process
        self.mode = mode
        self.n_shards = mesh.devices.size
        self.kernels = ShardedLedgerKernels(mesh, process)
        self.state = init_sharded_state(mesh, process)
        self.hazards = HazardTracker()
        # Per-shard occupancy guard (conservative: counts submissions, not
        # just successes; reconciled in execute_dense). Owner-hash skew means
        # one shard can fill well before aggregate capacity.
        self._acct_used = np.zeros(self.n_shards, dtype=np.int64)
        self._xfer_used = np.zeros(self.n_shards, dtype=np.int64)
        self._acct_limit = (1 << process.account_slots_log2) // 2
        self._xfer_limit = (1 << process.transfer_slots_log2) // 2

    def _shard_counts(self, arr: np.ndarray) -> np.ndarray:
        owners = owner_of_ids_np(arr["id_lo"], arr["id_hi"], self.n_shards)
        return np.bincount(owners, minlength=self.n_shards)

    def execute_dense(self, operation, timestamp: int, events) -> list[int]:
        from tigerbeetle_tpu import types as t

        n = len(events)
        n_pad = _next_pow2(n)
        if operation == Operation.create_transfers:
            arr = events if isinstance(events, np.ndarray) else t.transfers_to_np(events)
            counts = self._shard_counts(arr)
            if ((self._xfer_used + counts) > self._xfer_limit).any():
                raise RuntimeError(
                    "a transfer shard is at its load-factor limit: grow "
                    "ConfigProcess.transfer_slots_log2 (per-shard capacity)"
                )
            mode = self.mode
            if mode == "auto":
                mode = "serial" if self.hazards.transfers_hazard(arr) else "fast"
            fn = (
                self.kernels.commit_transfers_fast
                if mode == "fast"
                else self.kernels.commit_transfers_serial
            )
            batch = transfers_to_batch(arr, n_pad)
            self._xfer_used += counts
        elif operation == Operation.create_accounts:
            arr = events if isinstance(events, np.ndarray) else t.accounts_to_np(events)
            counts = self._shard_counts(arr)
            if ((self._acct_used + counts) > self._acct_limit).any():
                raise RuntimeError(
                    "an account shard is at its load-factor limit: grow "
                    "ConfigProcess.account_slots_log2 (per-shard capacity)"
                )
            mode = self.mode
            if mode == "auto":
                mode = "serial" if self.hazards.accounts_hazard(arr) else "fast"
            self.hazards.note_limit_accounts(arr)
            fn = (
                self.kernels.commit_accounts_fast
                if mode == "fast"
                else self.kernels.commit_accounts_serial
            )
            batch = accounts_to_batch(arr, n_pad)
            self._acct_used += counts
        else:
            raise AssertionError(operation)
        self.state, results = fn(
            self.state, batch, jnp.int32(n), jnp.uint64(timestamp)
        )
        dense = [int(x) for x in np.asarray(results)[:n]]
        self.check_fault()
        # Reconcile the conservative per-shard estimate to the exact
        # ever-applied count (rolled-back inserts tombstone their slot on the
        # owner shard and still occupy it — see models.ledger.applied_insert_mask).
        from tigerbeetle_tpu.models.ledger import applied_insert_mask

        not_applied = ~applied_insert_mask(dense, arr["flags"])
        if not_applied.any():
            owners = owner_of_ids_np(
                arr["id_lo"][not_applied], arr["id_hi"][not_applied], self.n_shards
            )
            dec = np.bincount(owners, minlength=self.n_shards)
            if operation == Operation.create_transfers:
                self._xfer_used -= dec
            else:
                self._acct_used -= dec
        return dense

    def check_fault(self) -> None:
        raise_on_fault(int(np.asarray(self.state["fault"])), "sharded ledger")

    # -- parity extraction (lookups come from HostLedgerBase) --

    def extract(self):
        """Pull the full sharded state to host dicts (accounts, transfers,
        posted) for bit-exact comparison against the oracle."""
        from tigerbeetle_tpu import types as t
        from tigerbeetle_tpu.models.ledger import _occupied_rows

        accounts: dict[int, object] = {}
        transfers: dict[int, object] = {}
        posted: dict[int, int] = {}
        acct = np.asarray(self.state["acct_rows"])
        xfer = np.asarray(self.state["xfer_rows"])
        ful = np.asarray(self.state["fulfill"])
        for s in range(self.n_shards):
            rows = acct[s][:-1]
            occ = _occupied_rows(rows)
            arr = np.frombuffer(rows[occ].tobytes(), dtype=t.ACCOUNT_DTYPE)
            for i in range(len(arr)):
                a = t.Account.from_np(arr[i])
                accounts[a.id] = a
            rows = xfer[s][:-1]
            occ = _occupied_rows(rows)
            arr = np.frombuffer(rows[occ].tobytes(), dtype=t.TRANSFER_DTYPE)
            fu = ful[s][:-1][occ]
            for i in range(len(arr)):
                x = t.Transfer.from_np(arr[i])
                transfers[x.id] = x
                if fu[i]:
                    posted[x.timestamp] = int(fu[i])
        return accounts, transfers, posted

    @property
    def commit_timestamp(self) -> int:
        return int(np.asarray(self.state["commit_ts"]))

    # -- checkpoint / state sync (the replica's blob snapshot seam) --

    _SNAP_SHARDED = (
        "acct_rows", "xfer_rows", "fulfill", "acct_claim", "xfer_claim",
        "bal_acc", "acct_used_slots", "xfer_used_slots",
    )
    _SNAP_REPLICATED = ("commit_ts", "acct_count", "xfer_count", "fault")

    def snapshot_bytes(self) -> bytes:
        """Serialize the full sharded state (one host pull per leaf) plus
        the host-side admission state — the replica checkpoints this as its
        snapshot blob, and state sync ships the same bytes. Byte-identical
        across replicas with identical histories (the determinism
        contract)."""
        import json

        self.check_fault()
        parts = [
            np.asarray(self.state[k]).tobytes()
            for k in self._SNAP_SHARDED + self._SNAP_REPLICATED
        ]
        h = self.hazards
        head = json.dumps({
            "n_shards": self.n_shards,
            "acct_slots_log2": self.process.account_slots_log2,
            "xfer_slots_log2": self.process.transfer_slots_log2,
            "sizes": [len(p) for p in parts],
            "acct_used": self._acct_used.tolist(),
            "xfer_used": self._xfer_used.tolist(),
            "amount_sum": str(h.amount_sum),
            "limit_account_ids": [str(x) for x in sorted(h.limit_account_ids)],
        }, sort_keys=True).encode()
        return len(head).to_bytes(4, "little") + head + b"".join(parts)

    def restore_bytes(self, raw: bytes) -> None:
        import json

        hn = int.from_bytes(raw[:4], "little")
        head = json.loads(raw[4 : 4 + hn])
        if (
            head["n_shards"] != self.n_shards
            or head["acct_slots_log2"] != self.process.account_slots_log2
            or head["xfer_slots_log2"] != self.process.transfer_slots_log2
        ):
            raise RuntimeError(
                "sharded checkpoint geometry mismatch: snapshot is "
                f"{head['n_shards']} shards @ 2^{head['acct_slots_log2']}/"
                f"2^{head['xfer_slots_log2']}, this mesh is "
                f"{self.n_shards} @ 2^{self.process.account_slots_log2}/"
                f"2^{self.process.transfer_slots_log2}"
            )
        fresh = init_sharded_state(self.mesh, self.process)
        off = 4 + hn
        names = self._SNAP_SHARDED + self._SNAP_REPLICATED
        for name, size in zip(names, head["sizes"]):
            ref = fresh[name]
            # .dtype/.shape are metadata — never np.asarray(ref) here (a
            # full d2h gather per leaf, twice, on the degrading transport)
            host = np.frombuffer(
                raw[off : off + size], dtype=ref.dtype
            ).reshape(ref.shape)
            fresh[name] = jax.device_put(jnp.asarray(host), ref.sharding)
            off += size
        self.state = fresh
        self._acct_used = np.array(head["acct_used"], dtype=np.int64)
        self._xfer_used = np.array(head["xfer_used"], dtype=np.int64)
        h = self.hazards
        h.amount_sum = int(head["amount_sum"])
        h.limit_account_ids = {int(x) for x in head["limit_account_ids"]}
        h._limit_lo = np.sort(np.array(
            [int(x) & ((1 << 64) - 1) for x in head["limit_account_ids"]],
            dtype=np.uint64,
        ))
