from tigerbeetle_tpu.parallel import mesh  # noqa: F401
