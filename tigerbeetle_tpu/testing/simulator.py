"""The VOPR-equivalent deterministic simulator (reference:
src/simulator.zig:66-173, SURVEY.md §4 tier 3).

One seed drives EVERYTHING — packet delays/loss/replay, partitions, the
crash/restart schedule, WAL fault injection, client workload and retry
timing — so a failing seed replays identically. The whole cluster (real
Replica code over MemoryStorage + PacketSimulator + per-replica skewed
DeterministicTime) runs in one process on virtual ticks.

Checkers (reference: src/testing/cluster/state_checker.zig,
storage_checker.zig):
- commit histories: every replica's committed (op -> checksum) stream must
  agree with every other's on common ops — one linear history, no forks;
- convergence after healing: all replicas reach the same commit_min;
- oracle parity: replaying the committed history through the scalar oracle
  must equal every replica's final extracted state bit-for-bit;
- liveness: the run must make progress within its tick budget.

The ledger backend is the scalar oracle by default (logic-level simulation
at high op counts); pass backend_factory=None ... DeviceLedger for
device-kernel runs (slower, used by a couple of seeds in CI).
"""

from __future__ import annotations

import random

from tigerbeetle_tpu import types
from tigerbeetle_tpu.constants import TEST_CLUSTER, ConfigCluster
from tigerbeetle_tpu.io.storage import MemoryStorage, Zone, ZoneLayout
from tigerbeetle_tpu.io.time import DeterministicTime
from tigerbeetle_tpu.models.oracle import OracleStateMachine
from tigerbeetle_tpu.state_machine import StateMachine
from tigerbeetle_tpu.testing.packet_simulator import (
    PacketSimulator,
    PacketSimulatorOptions,
)
from tigerbeetle_tpu.testing.workload import WorkloadGenerator
from tigerbeetle_tpu.types import Operation
from tigerbeetle_tpu.vsr.client import Client, RequestTimeout, SessionEvicted
from tigerbeetle_tpu.vsr.durable import format_data_file
from tigerbeetle_tpu.vsr.header import Header
from tigerbeetle_tpu.vsr.replica import Replica

CLIENT_ID_BASE = 1 << 64
CLIENT_RETRY_TICKS = 30


def _apply_op_lines(store, lines: list[str]) -> bool:
    """One op's records into a durable consumer store, APPLY-ONCE by op
    (shared by the single consumer and every fan-out consumer): ops at
    or below the applied high-water mark are redeliveries and must
    change nothing; gap records clip to unapplied ops."""
    import json as _json

    store.raw_lines.extend(lines)
    first = _json.loads(lines[0])
    if first.get("kind") == "gap":
        # clip to ops not already applied: a post-crash pump resuming
        # from the cursor may declare a span overlapping applied-but-
        # unacked ops — for those this is just redelivery-as-gap (the
        # store already holds them), not lost history
        lo = max(first["from"], store.applied_op + 1)
        if lo <= first["to"]:
            store.gaps.append((lo, first["to"]))
            store.stream.extend(lines)
        else:
            store.redelivered_ops += 1
        store.applied_op = max(store.applied_op, first["to"])
        return True
    op = first["op"]
    if op <= store.applied_op:
        store.redelivered_ops += 1
        return True  # dedup: accepted, zero effect
    store.stream.extend(lines)
    store.applied_ops.append(op)
    store.applied_op = op
    for line in lines:
        rec = _json.loads(line)
        for account, field, amount in rec.get("deltas", ()):
            acct = store.balances.setdefault(account, {})
            acct[field] = acct.get(field, 0) + amount
    return True


class SimCdcConsumer:
    """Deterministic CDC consumer for the VOPR: tails one replica's
    committed stream through a REAL CdcPump into a durable store, with a
    seeded crash/restart schedule for the consumer itself (the subsystem's
    fault model: the pump and its live window are volatile; the cursor and
    the downstream store survive, exactly what a process crash leaves).

    Redelivery happens whenever a crash lands between sink-accept and
    cursor-ack (and whenever the tailed replica itself restarts and
    re-commits from its checkpoint) — the store dedups at APPLY time by
    op, which is the at-least-once contract under test: raw_lines may
    carry duplicates, `stream`/`balances` must not."""

    def __init__(self, sim: "Simulator", index: int, seed: int,
                 crash_probability: float = 0.01,
                 restart_ticks_max: int = 40):
        self.sim = sim
        self.index = index
        self.rng = random.Random(seed * 19 + 5)
        self.crash_probability = crash_probability
        self.restart_ticks_max = restart_ticks_max
        from tigerbeetle_tpu.cdc import MemoryCursor

        # durable across consumer crashes
        self.cursor = MemoryCursor()
        self.raw_lines: list[str] = []  # as delivered (may hold dups)
        self.stream: list[str] = []  # deduped applied stream
        self.applied_ops: list[int] = []
        self.applied_op = 0
        self.balances: dict[int, dict[str, int]] = {}
        self.gaps: list[tuple[int, int]] = []
        self.redelivered_ops = 0
        self.crashes = 0
        # volatile
        self._pump = None
        self._down_until: int | None = None

    # -- the durable downstream store, as a sink --

    def emit_lines(self, lines: list[str]) -> bool:
        """One op's records (the pump emits op-atomically). Apply-once:
        ops at or below the applied high-water mark are redeliveries and
        must change nothing."""
        return _apply_op_lines(self, lines)

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass

    # -- lifecycle --

    def _attach(self) -> None:
        from tigerbeetle_tpu.cdc import CdcPump

        self._pump = CdcPump(
            self.sim.replicas[self.index], self, self.cursor,
            window=32, ack_interval=4,
        )
        self._pump.attach()

    def tick(self, now: int) -> None:
        if self._down_until is not None:
            if now < self._down_until:
                return
            self._down_until = None
        if self._pump is not None and self.rng.random() < self.crash_probability:
            # consumer crash: the pump, its live window, and any progress
            # past the last cursor ack are gone
            self.crashes += 1
            self._pump.detach()
            self._pump = None
            self._down_until = now + self.rng.randint(
                5, self.restart_ticks_max
            )
            return
        if self._pump is None:
            self._attach()
        elif self._pump.replica is not self.sim.replicas[self.index]:
            # the tailed replica restarted: re-subscribe to the new
            # process (its recovery re-commits redeliver; the store dedups)
            self._pump.detach()
            self._attach()
        if self.index in self.sim.down:
            return  # tailed replica down: the stream simply stalls
        self._pump.pump(budget_ops=4)

    def drain(self, budget_turns: int = 2000) -> None:
        """Post-heal: stream everything committed (no more crashes)."""
        self.crash_probability = 0.0
        if self._pump is None or (
            self._pump.replica is not self.sim.replicas[self.index]
        ):
            if self._pump is not None:
                self._pump.detach()
            self._attach()
        r = self.sim.replicas[self.index]
        for _ in range(budget_turns):
            self._pump.pump(budget_ops=16)
            if self._pump.next_op > r.commit_min:
                return
        raise AssertionError(
            f"cdc consumer failed to drain: next_op={self._pump.next_op} "
            f"commit_min={r.commit_min}"
        )


class _FanoutStore:
    """One fan-out consumer's durable downstream store (the sink +
    apply-once dedup of SimCdcConsumer, without its crash schedule).
    `throttle_every=k` models a slow consumer: every emission except
    each k-th is REFUSED — count-based, so the refusal pattern is
    deterministic and tick-independent."""

    def __init__(self, throttle_every: int = 0):
        self.throttle_every = throttle_every
        self.raw_lines: list[str] = []
        self.stream: list[str] = []
        self.applied_ops: list[int] = []
        self.applied_op = 0
        self.balances: dict[int, dict[str, int]] = {}
        self.gaps: list[tuple[int, int]] = []
        self.redelivered_ops = 0
        self.refusals = 0
        self._attempts = 0

    def emit_lines(self, lines: list[str]) -> bool:
        if self.throttle_every:
            self._attempts += 1
            if self._attempts % self.throttle_every:
                self.refusals += 1
                return False
        return _apply_op_lines(self, lines)

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class SimCdcFanout:
    """N CDC consumers over ONE shared tail (the ingress fan-out hub,
    tigerbeetle_tpu/ingress/fanout.py) on replica `index`. The LAST
    consumer is throttled (its sink refuses all but every k-th
    emission): the backpressure-isolation contract under test is that
    its lag grows while every other consumer's stays bounded — one slow
    consumer pauses only its own cursor. Cursors are durable
    (MemoryCursor), the hub volatile: a tailed-replica restart rebuilds
    the hub and consumers resume from their cursors (redeliveries
    dedup, like the single-consumer model)."""

    THROTTLED = "slow"

    def __init__(self, sim: "Simulator", index: int, seed: int,
                 n_consumers: int, throttle_every: int = 4):
        assert n_consumers >= 2
        from tigerbeetle_tpu.cdc import MemoryCursor

        self.sim = sim
        self.index = index
        self.n_consumers = n_consumers
        self.throttle_every = throttle_every
        self.stores: dict[str, _FanoutStore] = {}
        self.cursors: dict[str, MemoryCursor] = {}
        for i in range(n_consumers - 1):
            self.stores[f"c{i}"] = _FanoutStore()
            self.cursors[f"c{i}"] = MemoryCursor()
        self.stores[self.THROTTLED] = _FanoutStore(
            throttle_every=throttle_every
        )
        self.cursors[self.THROTTLED] = MemoryCursor()
        self.lag_max: dict[str, int] = {n: 0 for n in self.stores}
        self.hub = None

    def _attach(self) -> None:
        from tigerbeetle_tpu.ingress import CdcFanoutHub

        aof = getattr(self.sim, "_fanout_aof", None)
        self.hub = CdcFanoutHub(
            self.sim.replicas[self.index], window=32,
            aof_path=aof.name if aof is not None else None,
        )
        for name, store in self.stores.items():
            self.hub.add_consumer(
                name, store, self.cursors[name], ack_interval=4
            )
        self.hub.attach()

    def tick(self, now: int) -> None:
        if self.hub is None:
            self._attach()
        elif self.hub.replica is not self.sim.replicas[self.index]:
            # the tailed replica restarted: re-subscribe; consumers
            # resume from their durable cursors (redeliveries dedup)
            self.hub.detach()
            self._attach()
        if self.index in self.sim.down:
            return  # tailed replica down: every consumer stalls
        self.hub.pump(budget_ops=4)
        for name, lag in self.hub.lag_ops().items():
            self.lag_max[name] = max(self.lag_max[name], lag)

    def drain(self, budget_turns: int | None = None) -> None:
        """Post-heal: every consumer streams to the committed head (the
        throttled sink accepts one emission per `throttle_every`
        attempts, so the budget scales with the op count)."""
        if self.hub is None or (
            self.hub.replica is not self.sim.replicas[self.index]
        ):
            if self.hub is not None:
                self.hub.detach()
            self._attach()
        r = self.sim.replicas[self.index]
        if budget_turns is None:
            budget_turns = 2000 + (self.throttle_every + 1) * r.commit_min
        for _ in range(budget_turns):
            self.hub.pump(budget_ops=16)
            if all(
                p.next_op > r.commit_min for p in self.hub.pumps.values()
            ):
                return
        raise AssertionError(
            "cdc fan-out failed to drain: "
            f"{[(n, p.next_op) for n, p in self.hub.pumps.items()]} "
            f"commit_min={r.commit_min}"
        )


class SimClient:
    """Workload driver riding the client RUNTIME: retries, exponential
    backoff, round-robin re-targeting, busy backoff and (opt-in)
    re-registration all happen inside Client.tick() — the driver only
    issues work and takes replies, the same contract the live chaos
    fleet runs under. Typed errors surface through poll(): an eviction
    is fatal unless the client auto-re-registers (then it is counted and
    the session resumes), a deadline expiry counts and the slot retries
    with fresh work."""

    def __init__(self, client: Client, seed: int, batch_size: int = 8,
                 workload_knobs: dict | None = None,
                 tick_stride: int = 1, tick_burst: int = 1):
        self.client = client
        self.gen = WorkloadGenerator(seed, **(workload_knobs or {}))
        self.batch = batch_size
        self.rng = random.Random(seed * 13 + 7)
        self.replies = 0
        self.batch_index = 0
        self.evictions = 0
        self.deadline_timeouts = 0
        # Clock-skew dial: this client's runtime clock ticks at a skewed
        # rate against sim time (stride > 1 = slow clock, burst > 1 =
        # fast clock), so timeout/backoff firing interleaves differently
        # per client — the "clock-skewed timeout firing" fault axis.
        self.tick_stride = tick_stride
        self.tick_burst = tick_burst

    drain_mode = False  # heal phase: finish in-flight work, issue nothing new
    # Issue probability per free-slot tick — the offered-load dial the
    # prodday twin's phases turn (tigerbeetle_tpu/prodday.py). Changing
    # duty changes WHICH draws issue work but every draw still happens,
    # so a timeline's load curve stays seed-deterministic.
    duty = 0.5

    def tick(self, now: int) -> None:
        c = self.client
        if now % self.tick_stride == 0:
            for _ in range(self.tick_burst):
                c.tick()
        try:
            c.poll()
        except SessionEvicted:
            self.evictions += 1
            if not c.auto_reregister:
                raise AssertionError("client evicted during simulation")
        except RequestTimeout:
            self.deadline_timeouts += 1
        if c.reply is not None:
            c.take_reply()
            self.replies += 1
        if self.drain_mode and c.in_flight is None:
            return
        if c.session == 0:
            if c.in_flight is None and not c._want_reregister:
                c.register()
            return
        if c.in_flight is None:
            # idle when the draw lands below (1 - duty): at the 0.5
            # default this is bit-for-bit the pre-duty behavior, so
            # every tuned seed in the test suite replays unchanged
            if self.rng.random() < 1.0 - self.duty:
                return  # idle this tick
            self.batch_index += 1
            if self.batch_index % 3 == 1:
                op, events = self.gen.gen_accounts_batch(self.batch)
                body = types.accounts_to_np(events).tobytes()
            else:
                op, events = self.gen.gen_transfers_batch(self.batch)
                body = types.transfers_to_np(events).tobytes()
            c.request(op, body)


class Simulator:
    def __init__(
        self,
        seed: int,
        replica_count: int = 3,
        standby_count: int = 0,
        n_clients: int = 2,
        ticks: int = 1500,
        cluster: ConfigCluster = TEST_CLUSTER,
        crash_probability: float = 0.002,
        restart_ticks_max: int = 80,
        wal_fault_probability: float = 0.2,
        torn_write_probability: float = 0.2,
        replies_fault_probability: float = 0.1,
        superblock_fault_probability: float = 0.1,
        grid_fault_probability: float = 0.0,
        grid_read_latency_s: float = 0.0,
        forest_blocks: int = 0,
        grid_size: int = 8 * 1024 * 1024,
        options: PacketSimulatorOptions | None = None,
        backend_factory=OracleStateMachine,
        process=None,
        client_batch: int = 8,
        workload_knobs: dict | None = None,
        trace_path: str | None = None,
        cdc_consumer: bool = False,
        cdc_crash_probability: float = 0.01,
        cdc_fanout: int = 0,
        cdc_fanout_throttle: int = 4,
        ingress_gateway: bool = False,
        storm_clients: int = 0,
        hash_log: tuple[str, str] | None = None,
        client_auto_reregister: bool = False,
        client_deadline_ticks: int = 0,
        client_tick_skew: bool = False,
        primary_crash_probability: float = 0.0,
        latency_sample_every: int = 0,
        tick_hook=None,
        commitment_interval: int = 0,
        tail_aof: bool = False,
    ):
        from tigerbeetle_tpu.constants import TEST_PROCESS

        self.process_config = process or TEST_PROCESS
        # set BEFORE the replica loop: every replica (including ones
        # rebuilt by crash/restart) must retain CDC reply bodies from its
        # first committed op, or a consumer resuming across a tailed-
        # replica restart reads the WAL with the reply ring empty and
        # streams result:null records
        self.cdc_enabled = cdc_consumer or cdc_fanout > 0 or tail_aof
        # Fan-out mode's AOF (see the cdc_fanout block below) — created
        # BEFORE the replica loop so replica 0 appends from op 1.
        # `tail_aof` forces it without fan-out consumers: an external
        # harness (SimFederation's settlement agent) tailing replica 0
        # needs the deep-resume source so its stream never gaps.
        self._fanout_aof = None
        if cdc_fanout or tail_aof:
            import tempfile

            self._fanout_aof = tempfile.NamedTemporaryFile(
                prefix="tb_sim_aof_", suffix=".aof", delete=False
            )
            self._fanout_aof.close()
        # Ingress gateway on every replica (tigerbeetle_tpu/ingress):
        # request admission through the credit regulator, saturated
        # requests answered with typed busy replies the seeded clients
        # retry through — set before the replica loop so restarted
        # replicas get their gateway back too.
        self.ingress_gateway = ingress_gateway
        # hash_log debugging surface (testing/hash_log.py; the reference's
        # -Dhash-log-mode): ("record"|"check", path). ONE log instance
        # lives across replica 0's crash/restarts — recovery re-commits
        # re-record/re-check identical entries (idempotent by op), and
        # check mode dies AT the first divergent op of a replayed seed.
        self.hash_log = None
        if hash_log is not None:
            from tigerbeetle_tpu.testing.hash_log import HashLog

            self.hash_log = HashLog(hash_log[0], path=hash_log[1])
        # Latency-anatomy sampling override (tigerbeetle_tpu/latency.py):
        # 0 keeps the replica default. Stamps ride the DeterministicTime
        # seam (virtual ticks), so forcing sample_every=1 must leave the
        # committed history byte-identical AND fold identical latency
        # histograms across runs of one seed (tests/test_latency.py).
        self.latency_sample_every = latency_sample_every
        # Checkpoint state commitments (federation/commitment.py): every
        # replica carries a CommitmentLog folding the backend fingerprint
        # at op multiples of the interval; _check re-derives the chain
        # from the god's-eye history through the oracle and compares
        # every replica's ring — set before the replica loop so rebuilt
        # replicas get their log back too.
        self.commitment_interval = commitment_interval
        self.seed = seed
        self.rng = random.Random(seed)
        self.ticks_budget = ticks
        self.cluster_config = cluster
        self.crash_probability = crash_probability
        self.restart_ticks_max = restart_ticks_max
        self.wal_fault_probability = wal_fault_probability
        self.torn_write_probability = torn_write_probability
        self.replies_fault_probability = replies_fault_probability
        self.superblock_fault_probability = superblock_fault_probability
        self.grid_fault_probability = grid_fault_probability
        self.backend_factory = backend_factory
        self.replica_count = replica_count  # ACTIVE replicas (quorums)
        self.standby_count = standby_count
        self.total_replicas = replica_count + standby_count

        # Deterministic tracer mode: spans from every replica's commit
        # path are timestamped with SIM TICKS (the virtual clock), and the
        # canonical dump is byte-identical across runs of the same seed —
        # two dumps of a diverging VOPR seed can be diffed directly. The
        # tracer is pure observation: enabling it must leave the committed
        # history unchanged (tested in tests/test_metrics.py).
        #
        # ONE tracer PER REPLICA (pid = replica index), surviving that
        # replica's crash/restarts, and the dump is the STITCHED cluster
        # trace (tracer.stitch): every span tagged with an op's trace id
        # (vsr/header.py trace_id) becomes a Perfetto flow linking the
        # op's legs across replica pids — and because ticks, ring
        # contents and the stitch are all deterministic, the same seed
        # still dumps byte-identical files.
        self.trace_path = trace_path
        self.tracers = None
        if trace_path is not None:
            from tigerbeetle_tpu.tracer import SimTracer

            self.tracers = [
                SimTracer(clock=lambda: self.net.tick_now, pid=i)
                for i in range(replica_count + standby_count)
            ]

        self.net = PacketSimulator(
            seed * 31 + 1, self.total_replicas,
            options or PacketSimulatorOptions(
                packet_loss_probability=0.02,
                packet_replay_probability=0.02,
                partition_probability=0.005,
            ),
        )
        self.layout = ZoneLayout(cluster, grid_size=grid_size,
                                 forest_blocks=forest_blocks)
        self.times = [
            DeterministicTime(offset_ns=self.rng.randint(-50, 50) * 1_000_000)
            for _ in range(self.total_replicas)
        ]
        self.storages = []
        self.replicas: list[Replica] = []
        # god's-eye committed history per replica:
        # op -> (checksum, operation, timestamp, body)
        self.histories: list[dict[int, tuple]] = [
            {} for _ in range(self.total_replicas)
        ]
        # Injected grid-read latency (through the Storage seam, reference:
        # src/testing/storage.zig read_latency): every forest-block read
        # costs real wall time. Replica behavior keys off VIRTUAL time
        # (ticks / the Time seam) and the spill IO rides the deterministic
        # executor, so a seeded run's committed history must be BYTE-
        # IDENTICAL with and without the latency — the proof that replica
        # spill/grid IO is off the hot loop rather than hidden in it.
        self.grid_read_latency_s = grid_read_latency_s
        self.grid_reads = 0

        def _grid_latency_hook(zone, offset, size):
            if zone is Zone.grid and offset >= self.layout.forest_offset:
                self.grid_reads += 1
                if self.grid_read_latency_s > 0.0:
                    import time as _time

                    _time.sleep(self.grid_read_latency_s)

        for i in range(self.total_replicas):
            storage = MemoryStorage(self.layout, seed=seed * 97 + i)
            format_data_file(storage, cluster)
            if forest_blocks:
                storage.read_hook = _grid_latency_hook
            self.storages.append(storage)
            self.replicas.append(self._make_replica(i))
        self.down: dict[int, int] = {}  # replica -> restart tick
        self.crashes = 0
        self.wal_faults = 0
        self.torn_writes = 0
        self.replies_faults = 0
        self.superblock_faults = 0
        self.grid_faults = 0

        # Client-runtime fault axes (all seed-deterministic): opt-in
        # automatic re-registration after eviction, per-request deadlines
        # (RequestTimeout), skewed client clocks, and targeted crashes of
        # the PRIMARY while client requests are in flight.
        self.client_auto_reregister = client_auto_reregister
        self.client_deadline_ticks = client_deadline_ticks
        self.client_tick_skew = client_tick_skew
        self.primary_crash_probability = primary_crash_probability
        self.primary_crashes = 0
        self._client_batch = client_batch
        self._workload_knobs = workload_knobs
        self.clients = [
            self._new_sim_client(i) for i in range(n_clients)
        ]

        # Deterministic CDC consumer (tigerbeetle_tpu/cdc): tails replica
        # 0's committed stream, with its own seeded crash/restart
        # schedule; _check proves no gaps and no duplicated effects.
        self.cdc = (
            SimCdcConsumer(self, 0, seed,
                           crash_probability=cdc_crash_probability)
            if cdc_consumer else None
        )

        # CDC fan-out: N consumers (one deliberately throttled) over ONE
        # shared tail on replica 0 — the ingress hub's backpressure-
        # isolation contract under the full fault mix. The tailed
        # replica writes an AOF (a real temp file; content is
        # deterministic in the seed): the throttled consumer lags past
        # the bounded reply-retention ring BY DESIGN, and the AOF-oracle
        # replay is the source that keeps its deep reads carrying EXACT
        # result codes — without it those ops would stream result:null
        # (the documented results_unknown degradation).
        self.cdc_fanout = (
            SimCdcFanout(self, 0, seed, cdc_fanout,
                         throttle_every=cdc_fanout_throttle)
            if cdc_fanout else None
        )

        # Connect storm: at a seed-drawn tick, `storm_clients` NEW
        # sessions register at once (every register is a consensus op
        # through admission) and then join the workload.
        self.storm_clients = storm_clients
        self.storm_tick = (
            self.rng.randint(ticks // 4, max(ticks // 2, ticks // 4 + 1))
            if storm_clients else None
        )
        self._storm_seed = seed
        # Scripted-scenario seam (tigerbeetle_tpu/prodday.py run_sim_twin):
        # called as tick_hook(sim, now) at the top of every tick, before
        # the seeded fault draws — a timeline can set client duty, fire
        # kill_primary(), flip wal_fault_probability or record a flight
        # entry at exact tick offsets while staying deterministic (any
        # rng the hook consumes is the sim's own, in tick order).
        self.tick_hook = tick_hook
        self._n_clients = n_clients
        # (_client_batch/_workload_knobs were stored above, before the
        # client list — _new_sim_client reads them)

    def _new_sim_client(self, i: int) -> SimClient:
        """One seeded workload client on the tick-driven runtime. The
        skew draws come from the client's OWN derived rng (not self.rng),
        so enabling skew never shifts the crash/fault schedule of a
        seed's other draws."""
        stride = burst = 1
        if self.client_tick_skew:
            skew_rng = random.Random(self.seed * 41 + i * 3 + 2)
            stride = skew_rng.choice((1, 1, 2, 3))
            burst = skew_rng.choice((1, 1, 2)) if stride == 1 else 1
        return SimClient(
            Client(
                CLIENT_ID_BASE + i, self.net, self.replica_count,
                request_timeout_ticks=CLIENT_RETRY_TICKS,
                # short runs need a snappy ladder: cap at 4x base (the
                # live default caps at 16x — seconds-scale wall time)
                max_backoff_exponent=2,
                ping_ticks=40,
                deadline_ticks=self.client_deadline_ticks,
                auto_reregister=self.client_auto_reregister,
            ),
            self.seed * 7 + i, batch_size=self._client_batch,
            workload_knobs=self._workload_knobs,
            tick_stride=stride, tick_burst=burst,
        )

    def _make_replica(self, i: int) -> Replica:
        r = Replica(
            i, self.replica_count, self.storages[i], self.net, self.times[i],
            self.cluster_config, self.process_config,
            backend_factory=self.backend_factory,
            standby_count=self.standby_count,
            tracer=self.tracers[i] if self.tracers is not None else None,
        )
        if self.latency_sample_every:
            r.latency.sample_every = self.latency_sample_every
        hist = self.histories[i]

        def hook(header: Header, body: bytes, _h=hist) -> None:
            prev = _h.get(header.op)
            if prev is not None and prev[0] != header.checksum:
                raise AssertionError(
                    f"replica {i}: op {header.op} committed twice with "
                    f"different checksums"
                )
            _h[header.op] = (
                header.checksum, header.operation, header.timestamp, body,
            )

        r.commit_hook = hook
        if i == 0 and self.hash_log is not None:
            # chains AFTER the history hook (attach composes); replica 0
            # only — every replica commits the same stream, and one
            # recording per seed is the reference's shape too
            self.hash_log.attach(r)
        r.cdc_retain = self.cdc_enabled  # restarts keep the reply ring on
        if i == 0 and getattr(self, "_fanout_aof", None) is not None:
            # the fan-out tail's deep-resume source; reopened append-only
            # across restarts (recovery re-commits append duplicates the
            # replay source skips — the PR-4 torn/duplicate contract)
            from tigerbeetle_tpu.aof import AOF

            r.aof = AOF(self._fanout_aof.name)
        if self.commitment_interval:
            from tigerbeetle_tpu.federation.commitment import CommitmentLog

            # before open(): the restart path restores the persisted
            # chain from checkpoint meta and the WAL-tail replay
            # re-records against it
            r.commitment_log = CommitmentLog(self.commitment_interval)
        # thread timing must not leak into seeded deterministic runs
        r.sync_payload_async = False
        r.open()
        if self.ingress_gateway:
            from tigerbeetle_tpu.ingress import IngressGateway

            IngressGateway(self.net, r).install()
        return r

    # -- fault scheduling --

    def kill_primary(self, now: int) -> bool:
        """Scripted targeted crash (the prodday twin's `kill_primary` /
        `gray_primary` events): SIGKILL the current primary if one is
        identifiable, up, and quorum can spare it. Unlike the
        probability-drawn `_maybe_crash` primary fault, this fires at an
        exact scripted tick; the crash itself still rides `_crash` (torn
        head, restart delay) so its draws stay in the seed's stream."""
        active_down = sum(1 for i in self.down if i < self.replica_count)
        if active_down >= (self.replica_count - 1) // 2:
            return False
        views = [
            self.replicas[i].view
            for i in range(self.replica_count)
            if i not in self.down and self.replicas[i].status == "normal"
        ]
        if not views:
            return False
        primary = max(views) % self.replica_count
        if primary in self.down:
            return False
        self.primary_crashes += 1
        self._crash(primary, now)
        return True

    def _crash(self, victim: int, now: int) -> None:
        self.crashes += 1
        if self.rng.random() < self.torn_write_probability:
            self._inject_torn_head(victim)
        self.net.crashed.add(victim)
        self.down[victim] = now + self.rng.randint(
            10, self.restart_ticks_max
        )

    def _maybe_crash(self, now: int) -> None:
        alive = [i for i in range(self.total_replicas) if i not in self.down]
        # quorum safety counts ACTIVE replicas only; standbys (index >=
        # replica_count) may crash freely — they hold no votes
        active_down = sum(1 for i in self.down if i < self.replica_count)
        max_down = (self.replica_count - 1) // 2
        # Targeted fault: SIGKILL-the-primary with client requests IN
        # FLIGHT — the failover transition the client runtime's
        # timeout -> re-target -> duplicate-reply-dedup ladder exists
        # for. Probability 0 (the default) draws nothing.
        if (
            self.primary_crash_probability
            and self.rng.random() < self.primary_crash_probability
            and active_down < max_down
            and any(c.client.in_flight is not None for c in self.clients)
        ):
            views = [
                self.replicas[i].view
                for i in range(self.replica_count)
                if i not in self.down and self.replicas[i].status == "normal"
            ]
            if views:
                primary = max(views) % self.replica_count
                if primary not in self.down:
                    self.primary_crashes += 1
                    self._crash(primary, now)
                    return
        if self.rng.random() < self.crash_probability:
            if active_down >= max_down:
                alive = [i for i in alive if i >= self.replica_count]
                if not alive:
                    return
            victim = self.rng.choice(alive)
            self._crash(victim, now)

    def _inject_torn_head(self, i: int) -> None:
        """Crash-point torn write: the victim's most recent journal write
        is cut mid-sector, modeling a crash DURING write_prepare
        (reference: src/simulator.zig:160-173 crash-point faults). Tears
        either the prepare body only (redundant header survives -> TORN
        slot, body repairable from any acker) or both rings (-> BLANK
        slot, an explicit nack in protocol-aware recovery).

        Fault atlas rule (reference: src/testing/storage.zig:1-25): only
        tear when at least one OTHER replica journaled the op, so a copy
        survives cluster-wide and a possibly-acked op cannot vanish."""
        victim = self.replicas[i]
        op = victim.op
        if op < 1 or victim.journal.read_prepare(op) is None:
            return
        # survivors must be VOTERS: every repair path fetches from active
        # replicas only, so a copy surviving solely on a standby is
        # unreachable — tearing the last voter copy would wedge the cluster
        survivors = any(
            self.replicas[j].journal.read_prepare(op) is not None
            for j in range(self.replica_count)
            if j != i
        )
        if not survivors:
            return
        cfg = self.cluster_config
        slot = victim.journal.slot_for_op(op)
        self.storages[i].fault(
            Zone.wal_prepares, slot * cfg.message_size_max + 160, 96
        )
        if self.rng.random() < 0.5:  # tear the redundant header too: BLANK
            self.storages[i].fault(Zone.wal_headers, slot * 128, 128)
        self.torn_writes += 1

    def _maybe_grid_fault(self) -> None:
        """Corrupt one acquired forest block on an ALIVE replica mid-
        workload — the scrub pass (or a commit tripping GridBlockCorrupt)
        must heal it from a peer before the run's state checks read the
        spilled tail (reference: src/testing/storage.zig:1-25 faults every
        zone; src/vsr/grid_blocks_missing.zig peer repair).

        Fault atlas rule: only fault an address for which at least one
        OTHER alive replica holds a verifiable copy (replicas' forests are
        bit-identical by determinism, but a peer may itself carry an
        unhealed fault at the same address)."""
        if self.grid_fault_probability <= 0.0:
            return
        if self.rng.random() >= self.grid_fault_probability:
            return
        alive = [i for i in range(self.total_replicas) if i not in self.down]
        self.rng.shuffle(alive)
        from tigerbeetle_tpu.lsm.grid import BLOCK_SIZE

        for i in alive:
            r = self.replicas[i]
            if r.forest is None:
                continue
            grid = r.forest.grid
            acquired = [
                a for a in range(1, grid.block_count + 1)
                if not grid.free_set.is_free(a)
            ]
            self.rng.shuffle(acquired)
            for a in acquired[:8]:
                if not grid.verify_block(a):
                    continue  # already faulted and not yet healed
                survivors = any(
                    self.replicas[j].forest is not None
                    and self.replicas[j].forest.grid.verify_block(a)
                    for j in alive
                    if j != i
                )
                if not survivors:
                    continue
                fo = self.layout.forest_offset
                self.storages[i].fault(
                    Zone.grid,
                    fo + (a - 1) * BLOCK_SIZE + self.rng.randrange(0, 1024),
                    64,
                )
                grid.cache.remove(a)  # the fault must be visible to reads
                self.grid_faults += 1
                return
            # no eligible block on this replica this tick: try the next

    def _maybe_restart(self, now: int) -> None:
        for i, when in list(self.down.items()):
            if now >= when:
                if self.rng.random() < self.wal_fault_probability:
                    self._inject_wal_fault(i)
                if self.rng.random() < self.replies_fault_probability:
                    self._inject_replies_fault(i)
                if self.rng.random() < self.superblock_fault_probability:
                    self._inject_superblock_fault(i)
                del self.down[i]
                self.net.crashed.discard(i)
                self.replicas[i] = self._make_replica(i)

    def _inject_replies_fault(self, i: int) -> None:
        """Corrupt one client_replies slot: the checksum-validated restore
        must read it as absent and fall back to the reply-lost paths
        (reference: src/testing/storage.zig faults every zone)."""
        slot = self.rng.randrange(self.cluster_config.reply_slot_count)
        self.storages[i].fault(
            Zone.client_replies,
            slot * self.cluster_config.message_size_max
            + self.rng.randrange(0, 256),
            64,
        )
        self.replies_faults += 1

    def _inject_superblock_fault(self, i: int) -> None:
        """Corrupt ONE of the superblock's redundant copies: the quorum
        (4 copies) must still open. Atlas rule: never more than one copy
        per restart (a lost quorum is a beyond-f fault)."""
        copy = self.rng.randrange(ZoneLayout.SUPERBLOCK_COPIES)
        self.storages[i].fault(
            Zone.superblock,
            copy * ZoneLayout.SUPERBLOCK_COPY_SIZE
            + self.rng.randrange(0, 1024),
            64,
        )
        self.superblock_faults += 1

    def _inject_wal_fault(self, i: int) -> None:
        """Corrupt one WAL prepare body on the restarting replica — the
        journal must detect it (faulty slot) and the repair path must
        refetch it from a peer.

        Fault atlas rule (reference: src/testing/storage.zig
        ClusterFaultAtlas — at least one valid copy must survive): only
        fault an op that EVERY other replica has committed (and therefore
        journaled), so the repair source set is a majority and no committed
        op can vanish from all logs."""
        others_min = min(
            (
                self.replicas[j].commit_min
                for j in range(self.replica_count)  # repair sources: voters
                if j != i
            ),
            default=0,  # single-voter cluster: no repair source, no fault
        )
        if others_min < 1:
            return
        victim_journal = self.replicas[i].journal
        lo = max(1, self.replicas[i].op - self.cluster_config.journal_slot_count + 1)
        if lo > others_min:
            return
        for _ in range(8):  # a few random probes for a fault-eligible slot
            op = self.rng.randint(lo, others_min)
            got = victim_journal.read_prepare(op)
            if got is None:
                continue
            slot = victim_journal.slot_for_op(op)
            msg_max = self.cluster_config.message_size_max
            if self.rng.random() < 0.3 and op > lo:
                # MISDIRECTED write (reference: src/vsr/journal.zig
                # decision-matrix rows): a checksum-VALID prepare lands in
                # the wrong slot — recovery must classify it (not trust
                # it) and repair this slot from the redundant evidence
                src_op = op - 1
                src = self.replicas[i].journal.read_prepare(src_op)
                if src is not None:
                    src_slot = victim_journal.slot_for_op(src_op)
                    raw = self.storages[i].read(
                        Zone.wal_prepares, src_slot * msg_max, msg_max
                    )
                    self.storages[i].write(
                        Zone.wal_prepares, slot * msg_max, raw
                    )
                    self.wal_faults += 1
                    return
            self.storages[i].fault(
                Zone.wal_prepares, slot * msg_max + 200, 64,
            )
            self.wal_faults += 1
            return

    # -- main loop --

    def step(self) -> None:
        """ONE simulation tick: fault draws, replica/client/CDC ticks,
        network delivery — the exact body `run()` repeats. Extracted so
        a composite harness (federation/sim.py SimFederation) can
        interleave several Simulators tick-by-tick and drive agents
        between them without forking the loop."""
        now = self.net.tick_now
        if self.tick_hook is not None:
            self.tick_hook(self, now)
        self._maybe_crash(now)
        self._maybe_grid_fault()
        self._maybe_restart(now)
        for i, r in enumerate(self.replicas):
            if i not in self.down:
                self.times[i].tick()
                r.tick()
        if self.storm_tick is not None and now >= self.storm_tick:
            self.storm_tick = None
            base = len(self.clients)
            for i in range(self.storm_clients):
                self.clients.append(self._new_sim_client(base + i))
        for c in self.clients:
            c.tick(now)
        if self.cdc is not None:
            self.cdc.tick(now)
        if self.cdc_fanout is not None:
            self.cdc_fanout.tick(now)
        self.net.tick()

    def run(self) -> dict:
        for _ in range(self.ticks_budget):
            self.step()

        try:
            self._heal_and_converge()
            self._check()
        finally:
            # dump even when a checker raises: a diverging seed's trace is
            # exactly the artifact worth diffing against a healthy replay
            if self.tracers is not None and self.trace_path is not None:
                from tigerbeetle_tpu.tracer import dump_stitched

                dump_stitched(
                    self.trace_path,
                    [tr.events_ordered() for tr in self.tracers],
                    labels=[
                        f"replica {i}" if i < self.replica_count
                        else f"standby {i}"
                        for i in range(len(self.tracers))
                    ],
                )
            # ...and a failing seed's hash-log recording is the artifact a
            # replay checks against (save in the finally for the same
            # reason the trace dumps there)
            if self.hash_log is not None and self.hash_log.mode == "record":
                self.hash_log.save()
            if self._fanout_aof is not None:
                import os as _os

                try:
                    _os.unlink(self._fanout_aof.name)
                except OSError:
                    pass
        committed = max(
            (max(h) if h else 0) for h in self.histories
        )
        out_cdc = {}
        if self.cdc is not None:
            out_cdc = {
                "cdc_records": len(self.cdc.stream),
                "cdc_crashes": self.cdc.crashes,
                "cdc_redelivered_ops": self.cdc.redelivered_ops,
                "cdc_gaps": len(self.cdc.gaps),
            }
        if self.cdc_fanout is not None:
            out_cdc["cdc_fanout_consumers"] = self.cdc_fanout.n_consumers
            out_cdc["cdc_fanout_lag_max"] = dict(self.cdc_fanout.lag_max)
            out_cdc["cdc_fanout_refusals"] = self.cdc_fanout.stores[
                SimCdcFanout.THROTTLED
            ].refusals
        if self.storm_clients:
            out_cdc["storm_clients"] = self.storm_clients
        if self.primary_crash_probability:
            out_cdc["primary_crashes"] = self.primary_crashes
        if self.client_auto_reregister:
            # every surfaced eviction pairs with one automatic re-register
            out_cdc["client_evictions"] = sum(
                c.evictions for c in self.clients
            )
        if self.client_deadline_ticks:
            out_cdc["client_deadline_timeouts"] = sum(
                c.deadline_timeouts for c in self.clients
            )
        if self.hash_log is not None:
            out_cdc["hash_log_mode"] = self.hash_log.mode
            # ops THIS RUN streamed/verified — in check mode len(entries)
            # is the preloaded recording and says nothing about coverage
            out_cdc["hash_log_ops"] = self.hash_log.ops_seen
        if self.commitment_interval:
            # chain head in the result dict: the vopr fleet JSONL (and
            # its hub replay comparison) then covers commitment
            # determinism for free
            cl = self.replicas[0].commitment_log
            out_cdc["commitment_head_op"] = cl.head_op
            out_cdc["commitment_head"] = cl.head
        return {
            "seed": self.seed,
            "committed_ops": committed,
            "replies": sum(c.replies for c in self.clients),
            **out_cdc,
            "crashes": self.crashes,
            "wal_faults": self.wal_faults,
            "torn_writes": self.torn_writes,
            "replies_faults": self.replies_faults,
            "superblock_faults": self.superblock_faults,
            "grid_faults": self.grid_faults,
            "grid_reads": self.grid_reads,
            "net": dict(self.net.stats),
            "view": self.replicas[0].view,
        }

    def _heal_and_converge(self) -> None:
        self.net.clear_partitions()
        self.net.options.partition_probability = 0.0
        self.net.options.packet_loss_probability = 0.0
        self.crash_probability = 0.0
        if self.cdc is not None:
            self.cdc.crash_probability = 0.0
        for c in self.clients:
            c.drain_mode = True
        for i in list(self.down):
            del self.down[i]
            self.net.crashed.discard(i)
            self.replicas[i] = self._make_replica(i)
        # The budget must cover a full capped-backoff retry CYCLE of the
        # client runtime (a request that spent the fault phase retrying
        # sits at the top of its ladder — base 30 * 2^4 plus jitter —
        # and may need several re-targeted fires to find the primary);
        # the loop exits at quiescence, so healthy seeds don't pay this.
        budget = 2400
        for _ in range(budget):
            for i, r in enumerate(self.replicas):
                self.times[i].tick()
                r.tick()
            for c in self.clients:
                c.tick(self.net.tick_now)
            if self.cdc is not None:
                self.cdc.tick(self.net.tick_now)
            if self.cdc_fanout is not None:
                self.cdc_fanout.tick(self.net.tick_now)
            self.net.tick()
            mins = {r.commit_min for r in self.replicas}
            stats = {r.status for r in self.replicas}
            if len(mins) == 1 and stats == {"normal"}:
                quiet = all(c.client.in_flight is None for c in self.clients)
                if quiet and self._grids_clean():
                    return
        raise AssertionError(
            f"no convergence within heal budget: commit_mins="
            f"{[r.commit_min for r in self.replicas]} "
            f"status={[r.status for r in self.replicas]} "
            f"views={[r.view for r in self.replicas]}"
        )

    def _grids_clean(self) -> bool:
        """Every replica's acquired forest blocks verify — injected grid
        faults must be detected (scrub pass) AND healed (peer repair)
        before the final state checks read the spilled tail. Only probed
        once commits/statuses have already converged, and skipped entirely
        when no grid fault was ever injected (checksumming every block of
        every replica per probe would be pure waste there)."""
        if self.grid_faults == 0:
            return True
        for r in self.replicas:
            if r.forest is None:
                continue
            grid = r.forest.grid
            for a in range(1, grid.block_count + 1):
                if not grid.free_set.is_free(a) and not grid.verify_block(a):
                    return False
        return True

    def _check(self) -> None:
        # 1. one linear history: common ops agree across replicas
        merged: dict[int, tuple] = {}
        for i, h in enumerate(self.histories):
            for op, rec in h.items():
                if op in merged:
                    assert merged[op][0] == rec[0], (
                        f"history fork at op {op} (replica {i})"
                    )
                else:
                    merged[op] = rec
        assert merged, "nothing committed"
        top = max(merged)
        assert set(merged) == set(range(1, top + 1)), "history has holes"

        # 2. convergence to the same commit point
        mins = {r.commit_min for r in self.replicas}
        assert mins == {top}, (mins, top)

        # 3. oracle replay parity, bit for bit, on every replica —
        # folding the commitment chain at every boundary when enabled,
        # so the god's-eye oracle derives the reference chain too
        clog = None
        if self.commitment_interval:
            from tigerbeetle_tpu.federation.commitment import CommitmentLog

            clog = CommitmentLog(self.commitment_interval)
        sm = StateMachine(OracleStateMachine(), self.cluster_config)
        for op in range(1, top + 1):
            _, operation, timestamp, body = merged[op]
            if operation != int(Operation.register):
                sm.commit(Operation(operation), timestamp, body)
            if clog is not None and clog.is_boundary(op):
                clog.record(op, sm.backend.fingerprint())
        oracle = sm.backend
        for r in self.replicas:
            accounts, transfers, posted = r.ledger.extract()
            assert accounts == oracle.accounts, f"replica {r.replica} accounts"
            assert transfers == oracle.transfers, f"replica {r.replica} transfers"
            assert posted == oracle.posted, f"replica {r.replica} posted"
            if clog is not None and r.commitment_log is not None:
                # the replica's device/native-fed chain must agree with
                # the oracle-derived reference at every overlapping
                # checkpoint AND at the head
                div = clog.first_divergence(r.commitment_log)
                assert div is None, (
                    f"replica {r.replica} commitment diverges at "
                    f"checkpoint op {div}"
                )
                assert r.commitment_log.head_op == clog.head_op, (
                    r.commitment_log.head_op, clog.head_op,
                )
                assert r.commitment_log.head == clog.head, (
                    f"replica {r.replica} commitment head "
                    f"{r.commitment_log.head:#x} != oracle {clog.head:#x} "
                    f"at op {clog.head_op}"
                )

        if self.cdc is not None:
            self.cdc.drain()
            self._check_cdc_store(self.cdc, merged, top)
        if self.cdc_fanout is not None:
            # EVERY consumer of the shared tail owes the full stream
            # contract independently — including the throttled one
            self.cdc_fanout.drain()
            for store in self.cdc_fanout.stores.values():
                self._check_cdc_store(store, merged, top)

    def _check_cdc_store(self, store, merged: dict[int, tuple],
                         top: int) -> None:
        """One consumer store's contract, against the god's-eye history:

        - coverage: applied ops + declared gaps tile every record-bearing
          committed op exactly once (no silent holes, no op both applied
          and declared gone);
        - no duplicated effects: the deduped stream must equal, line for
          line, a reference encoding of the true history (the oracle
          regenerates exact reply buffers) — a record applied twice, out
          of order, or with drifted content all fail the same assert;
        - balance materialization: the consumer's delta-accumulated
          balances equal the reference's (apply-once proven on the
          numbers, not just the lines)."""
        import json as _json

        from tigerbeetle_tpu.cdc.record import encode_batch, record_line

        create_ops = (
            int(Operation.create_accounts), int(Operation.create_transfers)
        )
        gap_ops: set[int] = set()
        for a, b in store.gaps:
            assert 1 <= a <= b <= top, (a, b, top)
            gap_ops.update(range(a, b + 1))
        applied = set(store.applied_ops)
        assert len(applied) == len(store.applied_ops), "op applied twice"
        assert not (applied & gap_ops), "op both applied and declared gone"
        expected_ops = {
            op for op in range(1, top + 1)
            if merged[op][1] in create_ops
        }
        assert applied == expected_ops - gap_ops, (
            "stream coverage hole: "
            f"missing={sorted(expected_ops - gap_ops - applied)[:8]} "
            f"extra={sorted(applied - expected_ops)[:8]}"
        )

        sm = StateMachine(OracleStateMachine(), self.cluster_config)
        expected_lines: list[str] = []
        expected_balances: dict[int, dict[str, int]] = {}
        for op in range(1, top + 1):
            _, operation, timestamp, body = merged[op]
            if operation not in create_ops:
                continue  # registers/lookups: no state change, no records
            reply = sm.commit(Operation(operation), timestamp, body)
            if op not in applied:
                continue  # declared gap: consumer never saw it
            for rec in encode_batch(
                Header(op=op, operation=operation, timestamp=timestamp),
                body, reply,
            ):
                expected_lines.append(record_line(rec))
                for account, field, amount in rec.get("deltas", ()):
                    acct = expected_balances.setdefault(account, {})
                    acct[field] = acct.get(field, 0) + amount
        actual = [
            line for line in store.stream
            if _json.loads(line).get("kind") != "gap"
        ]
        assert actual == expected_lines, (
            f"cdc stream drift: {len(actual)} vs {len(expected_lines)} lines"
        )
        assert store.balances == expected_balances, "duplicated effects"


def run_simulation(seed: int, **kwargs) -> dict:
    return Simulator(seed, **kwargs).run()


def random_options(seed: int, device_fraction: float = 0.0) -> dict:
    """Seed-derived cluster topology + fault mix for the VOPR fleet
    (reference: src/simulator.zig:66-152 — cluster size, client count, and
    every fault probability drawn from the seed; :160-173 crash-point
    faults). The draw is deterministic in `seed`, so a failing fleet seed
    replays with the identical topology.

    Most seeds run the scalar-oracle backend (logic-level, fast) with the
    full chaos mix: 1-6 replicas, 0-2 standbys, 1-8 clients, partitions +
    torn writes + WAL/replies/superblock faults all active together. A
    `device_fraction` slice instead runs the DeviceLedger backend with a
    tiny spill-heavy table + grid faults (grid faults need a forest, which
    only the device backend owns), still combined with partitions, crashes
    and torn writes — the combination the round-4 verdict called out as
    never explored."""
    rng = random.Random(seed ^ 0x56303552)  # "V05R"
    opts: dict = {
        "replica_count": rng.randint(1, 6),
        "standby_count": rng.randint(0, 2),
        "n_clients": rng.randint(1, 8),
        "client_batch": rng.choice((1, 2, 4, 8, 16)),
        "crash_probability": rng.uniform(0.0, 0.004),
        "restart_ticks_max": rng.randint(40, 120),
        "wal_fault_probability": rng.uniform(0.0, 0.35),
        "torn_write_probability": rng.uniform(0.0, 0.35),
        "replies_fault_probability": rng.uniform(0.0, 0.25),
        "superblock_fault_probability": rng.uniform(0.0, 0.25),
        "options": PacketSimulatorOptions(
            one_way_delay_min=rng.randint(1, 2),
            one_way_delay_max=rng.randint(3, 10),
            packet_loss_probability=rng.uniform(0.0, 0.06),
            packet_replay_probability=rng.uniform(0.0, 0.06),
            partition_probability=rng.uniform(0.0, 0.012),
            unpartition_probability=rng.uniform(0.05, 0.4),
            partition_symmetry_probability=rng.uniform(0.3, 1.0),
        ),
        "workload_knobs": {
            "ledgers": rng.choice(((1,), (1, 2), (1, 2, 3))),
            "invalid_rate": rng.uniform(0.0, 0.3),
            "conflict_rate": rng.uniform(0.0, 0.4),
            "chain_rate": rng.uniform(0.0, 0.25),
            "two_phase_rate": rng.uniform(0.0, 0.4),
            "balancing_rate": rng.uniform(0.0, 0.2),
            "limit_account_rate": rng.uniform(0.0, 0.3),
        },
    }
    if rng.random() < device_fraction:
        from tigerbeetle_tpu.constants import ConfigProcess

        # device-backend spill seed: the grid-fault atlas needs >= 2
        # replicas holding verifiable peer copies, and the compile-bound
        # device runs cap the tick budget and client count
        opts.update(
            backend_factory=None,
            replica_count=max(2, min(3, opts["replica_count"])),
            standby_count=0,
            n_clients=1,
            client_batch=24,
            ticks=300,
            grid_fault_probability=rng.uniform(0.05, 0.2),
            forest_blocks=192,
            grid_size=64 * 1024 * 1024,
            process=ConfigProcess(
                account_slots_log2=10, transfer_slots_log2=7,
                lsm_memtable_max=48,
            ),
            workload_knobs=dict(
                ledgers=(1,), invalid_rate=0.0,
                conflict_rate=rng.uniform(0.0, 0.05), chain_rate=0.0,
                two_phase_rate=rng.uniform(0.05, 0.15),
                balancing_rate=0.0, limit_account_rate=0.0,
            ),
        )
    return opts


def describe_options(opts: dict) -> str:
    """One-line topology/fault summary for fleet logs (replayability:
    the seed alone reproduces the draw, this line makes it legible)."""
    o = opts.get("options")
    backend = "device" if opts.get("backend_factory", "x") is None else "oracle"
    parts = [
        f"r{opts['replica_count']}+s{opts['standby_count']}",
        f"c{opts['n_clients']}x{opts['client_batch']}",
        backend,
        f"crash={opts['crash_probability']:.4f}",
        f"wal={opts['wal_fault_probability']:.2f}",
        f"torn={opts['torn_write_probability']:.2f}",
    ]
    if opts.get("grid_fault_probability"):
        parts.append(f"grid={opts['grid_fault_probability']:.2f}")
    if o is not None:
        parts.append(
            f"loss={o.packet_loss_probability:.3f}"
            f"/part={o.partition_probability:.4f}"
        )
    return " ".join(parts)
