"""Per-structure fuzzers (tier 4).

The reference runs a dedicated fuzzer per load-bearing data structure
(reference: build.zig:508-558 — fuzz_ewah, fuzz_lsm_tree, fuzz_lsm_forest,
fuzz_lsm_manifest_log, fuzz_lsm_cache_map, fuzz_vsr_journal_format,
fuzz_vsr_superblock, fuzz_vsr_superblock_free_set; shared helpers
src/testing/fuzz.zig). Each fuzzer here is a seeded function
``fuzz_*(seed, steps)`` that drives the structure against an oracle model
(or an invariant set) and raises AssertionError on any divergence:

- the pytest tier runs every fuzzer with bounded steps (tests/test_fuzz.py);
- ``scripts/fuzz.py`` loops seeds indefinitely (the fuzz_loop.sh analog).

Corruption-facing fuzzers (journal format, superblock) assert the
recovery paths never crash and never accept corrupt data silently.
"""

from __future__ import annotations

import random

from tigerbeetle_tpu import stdx
from tigerbeetle_tpu.constants import TEST_CLUSTER
from tigerbeetle_tpu.io.storage import MemoryStorage, Zone, ZoneLayout
from tigerbeetle_tpu.lsm.cache import SetAssociativeCache
from tigerbeetle_tpu.lsm.grid import Grid
from tigerbeetle_tpu.lsm.groove import Forest
from tigerbeetle_tpu.lsm.tree import Tree
from tigerbeetle_tpu.vsr.free_set import FreeSet
from tigerbeetle_tpu.vsr.header import Command, Header
from tigerbeetle_tpu.vsr.journal import Journal
from tigerbeetle_tpu.vsr.superblock import SuperBlock, VSRState

_LAYOUT = ZoneLayout(TEST_CLUSTER, grid_size=96 * 1024 * 1024)


def _grid(storage=None, blocks=640):
    storage = storage or MemoryStorage(_LAYOUT)
    return storage, Grid(storage, offset=0, block_count=blocks,
                         cache_blocks=64)


# ----------------------------------------------------------------------
# fuzz_ewah (reference: src/ewah.zig codec)
# ----------------------------------------------------------------------


def fuzz_ewah(seed: int, steps: int = 200) -> None:
    rng = random.Random(seed)
    for _ in range(steps):
        n = rng.randint(1, 256)
        style = rng.random()
        if style < 0.3:  # long runs (the codec's compression case)
            words, w = [], 0
            while len(words) < n:
                run = rng.randint(1, n - len(words))
                w = rng.choice((0, (1 << 64) - 1, rng.getrandbits(64)))
                words += [w] * run
        else:
            words = [rng.getrandbits(64) for _ in range(n)]
        enc = stdx.ewah_encode(words)
        dec = stdx.ewah_decode(enc, len(words))
        assert dec == words, f"ewah roundtrip diverged (seed {seed})"


# ----------------------------------------------------------------------
# fuzz_lsm_tree (reference: fuzz_lsm_tree.zig — ops vs a model)
# ----------------------------------------------------------------------


def fuzz_lsm_tree(seed: int, steps: int = 1500) -> None:
    rng = random.Random(seed)
    _, grid = _grid()
    tree = Tree(grid, key_size=8, value_size=8,
                memtable_max=rng.choice((16, 32, 64)))
    model: dict[bytes, bytes] = {}
    keyspace = rng.choice((64, 512, 4096))
    for step in range(steps):
        roll = rng.random()
        k = rng.randrange(keyspace).to_bytes(8, "big")
        if roll < 0.55:
            v = rng.getrandbits(63).to_bytes(8, "big")
            tree.put(k, v)
            model[k] = v
        elif roll < 0.75:
            tree.remove(k)
            model.pop(k, None)
        elif roll < 0.95:
            assert tree.get(k) == model.get(k), (seed, step)
        else:
            tree.flush()
            if rng.random() < 0.3:
                # checkpoint analog: staged frees become reusable (without
                # this, compaction churn exhausts the grid by design —
                # frees only apply at checkpoints)
                grid.encode_free_set()
    tree.flush()
    for k, v in model.items():
        assert tree.get(k) == v, (seed, k)
    lo = rng.randrange(keyspace).to_bytes(8, "big")
    hi = rng.randrange(keyspace).to_bytes(8, "big")
    if lo > hi:
        lo, hi = hi, lo
    expect = sorted((k, v) for k, v in model.items() if lo <= k <= hi)
    assert tree.range(lo, hi) == expect, seed
    # levels >= 1 must stay disjoint and sorted
    for level in tree.levels[1:]:
        for a, b in zip(level, level[1:]):
            assert a.key_max < b.key_min, (seed, "level overlap")


# ----------------------------------------------------------------------
# fuzz_lsm_forest (reference: fuzz_lsm_forest.zig — checkpoint/restore)
# ----------------------------------------------------------------------


def fuzz_lsm_forest(seed: int, steps: int = 400) -> None:
    rng = random.Random(seed)
    storage, grid = _grid()
    forest = Forest(grid)
    model: dict[int, tuple[int, bytes]] = {}
    ts = 0
    meta = None
    for step in range(steps):
        roll = rng.random()
        if roll < 0.7 or not model:
            id_ = rng.randrange(1, 4096)
            ts += 1
            # 0..254: an all-0xFF row is the tombstone encoding, which real
            # wire rows can never be (all-ones ids are invalid,
            # reference: src/tigerbeetle.zig:160-163)
            row = bytes([rng.randrange(255)]) * 128
            forest.transfers.insert(id_, ts, row)
            model[id_] = (ts, row)
        elif roll < 0.9:
            id_ = rng.choice(list(model))
            g = forest.transfers
            ts_key = g.ids.get(g._id_key(id_))
            assert ts_key is not None, (seed, step, id_)
            assert g.objects.get(ts_key) == model[id_][1], (seed, step)
        else:
            meta = forest.checkpoint()
    meta = forest.checkpoint()
    # restart: fresh forest over the same storage
    _, grid2 = _grid(storage)
    forest2 = Forest(grid2)
    forest2.restore(meta)
    for id_, (_, row) in model.items():
        g = forest2.transfers
        ts_key = g.ids.get(g._id_key(id_))
        assert ts_key is not None, (seed, id_)
        assert g.objects.get(ts_key) == row, (seed, id_)


# ----------------------------------------------------------------------
# fuzz_lsm_manifest_log (reference: fuzz_lsm_manifest_log.zig)
# ----------------------------------------------------------------------


def fuzz_lsm_manifest_log(seed: int, steps: int = 60) -> None:
    """Random churn + multiple checkpoints; every checkpoint's meta must
    restore to exactly the live table metadata at that instant."""
    rng = random.Random(seed)
    storage, grid = _grid()
    forest = Forest(grid)
    ts = 0
    for _ in range(steps):
        for _ in range(rng.randint(10, 120)):
            ts += 1
            forest.transfers.insert(rng.randrange(1, 2000), ts,
                                    bytes([ts % 251]) * 128)
        meta = forest.checkpoint()
        snapshot = [
            [i.to_json() for i in lv]
            for tree in forest._trees()
            for lv in tree.levels
            if lv
        ]
        _, grid2 = _grid(storage)
        forest2 = Forest(grid2)
        forest2.restore(meta)
        snapshot2 = [
            [i.to_json() for i in lv]
            for tree in forest2._trees()
            for lv in tree.levels
            if lv
        ]
        assert snapshot == snapshot2, seed


# ----------------------------------------------------------------------
# fuzz_cache_map analog: the set-associative cache
# ----------------------------------------------------------------------


def fuzz_sac(seed: int, steps: int = 5000) -> None:
    """A cache may evict, but must NEVER return a wrong value, and a
    just-put key must be immediately readable."""
    rng = random.Random(seed)
    cap = rng.choice((16, 64, 256))
    cache = SetAssociativeCache(cap)
    model: dict[int, int] = {}
    for step in range(steps):
        k = rng.randrange(cap * 4)
        roll = rng.random()
        if roll < 0.5:
            v = rng.getrandbits(32)
            cache.put(k, v)
            model[k] = v
            assert cache.get(k) == v, (seed, step)
        elif roll < 0.9:
            got = cache.get(k)
            assert got is None or got == model.get(k), (seed, step)
        else:
            cache.remove(k)
            assert cache.get(k) is None, (seed, step)


# ----------------------------------------------------------------------
# fuzz_vsr_superblock_free_set (reference: fuzz_vsr_superblock_free_set.zig)
# ----------------------------------------------------------------------


def fuzz_free_set(seed: int, steps: int = 2000) -> None:
    rng = random.Random(seed)
    count = rng.choice((64, 256, 1024))
    fs = FreeSet(count)
    acquired: set[int] = set()
    for step in range(steps):
        roll = rng.random()
        if roll < 0.55:
            want = rng.randint(1, 8)
            r = fs.reserve(want)
            if r is not None:
                for _ in range(rng.randint(0, want)):
                    a = fs.acquire(r)
                    if a is None:
                        break
                    assert a not in acquired, (seed, step, "double acquire")
                    acquired.add(a)
                fs.forfeit(r)
            else:
                assert fs.count_free() < want, (seed, step)
        elif roll < 0.85 and acquired:
            a = rng.choice(sorted(acquired))
            fs.release(a)
            acquired.discard(a)
        else:
            # encode/decode roundtrip preserves exact state
            fs2 = FreeSet.decode(fs.encode(), count)
            assert fs2.count_free() == fs.count_free(), (seed, step)
            assert all(not fs2.is_free(a) for a in acquired), (seed, step)
    assert fs.count_free() == count - len(acquired), seed


# ----------------------------------------------------------------------
# fuzz_vsr_journal_format (reference: fuzz_vsr_journal_format.zig —
# recovery over arbitrary bytes must classify, never crash or accept junk)
# ----------------------------------------------------------------------


def fuzz_journal_format(seed: int, steps: int = 20) -> None:
    rng = random.Random(seed)
    for _ in range(steps):
        storage = MemoryStorage(_LAYOUT)
        journal = Journal(storage, TEST_CLUSTER)
        written: dict[int, bytes] = {}
        for op in range(1, rng.randint(2, 40)):
            body = rng.randbytes(rng.randrange(0, 512))
            h = Header(command=int(Command.prepare), op=op,
                       operation=130, timestamp=op * 10)
            h.set_checksum_body(body)
            h.set_checksum()
            journal.write_prepare(h, body)
            written[op] = body
        # corrupt random WAL ranges (headers and prepares zones)
        for _ in range(rng.randrange(0, 6)):
            zone = rng.choice((Zone.wal_headers, Zone.wal_prepares))
            size = _LAYOUT.sizes[zone]
            off = rng.randrange(0, size - 64)
            storage.fault(zone, off, rng.randint(1, 4096))
        j2 = Journal(storage, TEST_CLUSTER)
        recovered = j2.recover()  # must never raise
        for op, header in recovered.items():
            got = j2.read_prepare(op)
            if got is not None:
                h2, body = got
                # anything recovery vouches for must be bit-exact
                assert body == written.get(op), (seed, op)
                assert h2.checksum == header.checksum, (seed, op)


# ----------------------------------------------------------------------
# fuzz_vsr_superblock (reference: fuzz_vsr_superblock.zig — quorum
# recovery under copy corruption)
# ----------------------------------------------------------------------


def fuzz_superblock(seed: int, steps: int = 40) -> None:
    rng = random.Random(seed)
    for _ in range(steps):
        storage = MemoryStorage(_LAYOUT)
        sb = SuperBlock(storage)
        last = None
        for seq in range(1, rng.randint(2, 6)):
            last = VSRState(cluster=7, replica=0, sequence=seq,
                            commit_min=seq * 10, commit_max=seq * 10,
                            meta={"m": str(seq)})
            sb.checkpoint(last)
        # corrupt up to 2 of the 4 copies: quorum must still recover the
        # LATEST state (reference: superblock_quorums.zig)
        n_corrupt = rng.randint(0, 2)
        size = ZoneLayout.SUPERBLOCK_COPY_SIZE
        for c in rng.sample(range(4), n_corrupt):
            storage.fault(Zone.superblock, c * size + rng.randrange(0, 4096),
                          rng.randint(1, 1024))
        sb2 = SuperBlock(storage)
        got = sb2.open()  # must never crash
        assert got.sequence == last.sequence, (seed, got.sequence)
        assert got.commit_min == last.commit_min, seed
        assert got.meta == last.meta, seed


ALL_FUZZERS = {
    "ewah": fuzz_ewah,
    "lsm_tree": fuzz_lsm_tree,
    "lsm_forest": fuzz_lsm_forest,
    "lsm_manifest_log": fuzz_lsm_manifest_log,
    "sac": fuzz_sac,
    "free_set": fuzz_free_set,
    "journal_format": fuzz_journal_format,
    "superblock": fuzz_superblock,
}
