"""hash_log: record/check divergence debugging between two runs
(reference: src/testing/hash_log.zig:1-5, armed by -Dhash-log-mode in
src/config.zig:195-199).

When two runs that SHOULD be identical (same seed, same inputs — e.g. a
single-chip vs sharded-mesh replica, or the same seed before/after a
kernel change) disagree, the state checkers only say the END states
differ. The hash log pinpoints the FIRST divergent commit: record mode
streams one hash per committed op — covering the prepare (op, checksum:
the consensus stream) AND the reply body checksum (the result codes: a
kernel nondeterminism with an identical log still diverges here) — and
check mode replays against the recording, failing with the exact op.
"""

from __future__ import annotations

import json

from tigerbeetle_tpu import native


def parse_hash_log_spec(spec: str) -> tuple[str, str]:
    """CLI surface parser (``start --hash-log``, ``vopr.py --hash-log``):
    ``record:<path>`` | ``check:<path>`` | bare ``<path>`` (records) ->
    (mode, path). The reference arms the same pair via -Dhash-log-mode
    (src/config.zig:195-199)."""
    mode, sep, path = spec.partition(":")
    if sep and mode in ("record", "check"):
        return mode, path
    return "record", spec


class HashLogDivergence(AssertionError):
    def __init__(self, op: int, kind: str, want: int, got: int):
        super().__init__(
            f"hash_log: first divergence at op {op} ({kind}): "
            f"recorded {want:#x}, this run {got:#x}"
        )
        self.op = op
        self.kind = kind


class HashLog:
    """mode="record": stream hashes into memory (save() persists).
    mode="check": every hash is compared as it happens — the run fails AT
    the first divergent op, not at the end."""

    def __init__(self, mode: str = "record", path: str | None = None):
        assert mode in ("record", "check")
        self.mode = mode
        self.path = path
        # op -> (prepare_checksum, reply_body_checksum | None)
        self.entries: dict[int, list] = {}
        # ops THIS RUN actually streamed/verified (check mode preloads
        # `entries` from the recording, so len(entries) says nothing
        # about replay coverage — a truncated replay must not read as
        # fully checked)
        self._seen: set[int] = set()
        if mode == "check":
            assert path is not None, "check mode needs a recording"
            with open(path) as f:
                for line in f:
                    rec = json.loads(line)
                    self.entries[int(rec["op"])] = [
                        int(rec["prepare"], 16),
                        int(rec["reply"], 16) if rec["reply"] else None,
                    ]

    @property
    def ops_seen(self) -> int:
        """Distinct ops this run recorded (record mode) or replayed
        against the recording (check mode) — the coverage number a
        surface should report, NOT len(entries)."""
        return len(self._seen)

    # -- wiring --

    def attach(self, replica) -> None:
        """Chain onto the replica's observation hooks (composes with an
        already-installed hook, e.g. the simulator's history recorder)."""
        prev_commit = replica.commit_hook
        prev_reply = replica.reply_hook

        def on_commit(header, body):
            if prev_commit is not None:
                prev_commit(header, body)
            self.note_prepare(header.op, header.checksum)

        def on_reply(header, reply_checksum):
            if prev_reply is not None:
                prev_reply(header, reply_checksum)
            self.note_reply(header.op, reply_checksum)

        replica.commit_hook = on_commit
        replica.reply_hook = on_reply

    # -- the stream --

    def note_prepare(self, op: int, checksum: int) -> None:
        self._seen.add(op)
        if self.mode == "record":
            self.entries.setdefault(op, [None, None])[0] = checksum
            return
        want = self.entries.get(op)
        if want is None:
            raise HashLogDivergence(op, "prepare-beyond-recording", 0, checksum)
        if want[0] is not None and want[0] != checksum:
            raise HashLogDivergence(op, "prepare", want[0], checksum)

    def note_reply(self, op: int, reply_checksum: int) -> None:
        if self.mode == "record":
            self.entries.setdefault(op, [None, None])[1] = reply_checksum
            return
        want = self.entries.get(op)
        if want is not None and want[1] is not None and want[1] != reply_checksum:
            raise HashLogDivergence(op, "reply", want[1], reply_checksum)

    # -- persistence --

    def save(self, path: str | None = None) -> str:
        path = path or self.path
        assert path is not None
        with open(path, "w") as f:
            for op in sorted(self.entries):
                pre, rep = self.entries[op]
                f.write(json.dumps({
                    "op": op,
                    "prepare": f"{pre:#x}" if pre is not None else "",
                    "reply": f"{rep:#x}" if rep is not None else "",
                }) + "\n")
        return path

    def digest(self) -> int:
        """One checksum over the whole stream (quick whole-run compare)."""
        acc = b"".join(
            op.to_bytes(8, "little")
            + (pre or 0).to_bytes(16, "little")
            + (rep or 0).to_bytes(16, "little")
            for op, (pre, rep) in sorted(self.entries.items())
        )
        return native.checksum(acc)
