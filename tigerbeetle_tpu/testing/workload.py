"""Randomized ledger workload generator.

The analog of the reference's workload generator (reference:
src/state_machine/workload.zig:1-19): produces seeded, reproducible batches of
create_accounts / create_transfers / lookup_* events covering the valid,
invalid, and intra-batch-conflicting regions of the input space — duplicate
ids, linked chains, two-phase pending/post/void (including in-batch
references), balancing transfers, balance-limit accounts, expired timeouts.

Used by the parity tests (device kernels vs. oracle) and the simulator's
auditor.
"""

from __future__ import annotations

import random

from tigerbeetle_tpu.constants import U128_MAX
from tigerbeetle_tpu.types import Account, AccountFlags, Operation, Transfer, TransferFlags


class WorkloadGenerator:
    def __init__(
        self,
        seed: int,
        *,
        ledgers: tuple[int, ...] = (1, 2),
        invalid_rate: float = 0.15,
        conflict_rate: float = 0.25,
        chain_rate: float = 0.1,
        two_phase_rate: float = 0.2,
        balancing_rate: float = 0.1,
        limit_account_rate: float = 0.15,
    ) -> None:
        self.rng = random.Random(seed)
        self.ledgers = ledgers
        self.invalid_rate = invalid_rate
        self.conflict_rate = conflict_rate
        self.chain_rate = chain_rate
        self.two_phase_rate = two_phase_rate
        self.balancing_rate = balancing_rate
        self.limit_account_rate = limit_account_rate
        self.next_id = 1
        self.account_ids: list[int] = []
        self.transfer_ids: list[int] = []
        self.pending_ids: list[int] = []

    def _fresh_id(self) -> int:
        i = self.next_id
        self.next_id += 1
        # Spread ids over the u128 space so hash paths are exercised.
        return (i * 0x9E3779B97F4A7C15) & (U128_MAX - 1) | 1

    def _account_id(self) -> int:
        rng = self.rng
        if self.account_ids and rng.random() > 0.1:
            return rng.choice(self.account_ids)
        return self._fresh_id()

    def gen_accounts_batch(self, size: int) -> tuple[Operation, list[Account]]:
        rng = self.rng
        events: list[Account] = []
        while len(events) < size:
            a = Account(
                id=self._fresh_id(),
                ledger=rng.choice(self.ledgers),
                code=rng.randint(1, 100),
                user_data_128=rng.randint(0, U128_MAX),
                user_data_64=rng.getrandbits(64),
                user_data_32=rng.getrandbits(32),
            )
            if rng.random() < self.limit_account_rate:
                a.flags |= rng.choice(
                    (
                        AccountFlags.debits_must_not_exceed_credits,
                        AccountFlags.credits_must_not_exceed_debits,
                    )
                )
            roll = rng.random()
            if roll < self.invalid_rate:
                mutation = rng.randrange(8)
                if mutation == 0:
                    a.id = 0
                elif mutation == 1:
                    a.id = U128_MAX
                elif mutation == 2:
                    a.ledger = 0
                elif mutation == 3:
                    a.code = 0
                elif mutation == 4:
                    a.debits_posted = rng.randint(1, 100)
                elif mutation == 5:
                    a.flags = int(a.flags) | (1 << rng.randint(3, 15))
                elif mutation == 6:
                    a.reserved = 1
                elif mutation == 7:
                    a.flags = int(
                        AccountFlags.debits_must_not_exceed_credits
                        | AccountFlags.credits_must_not_exceed_debits
                    )
            elif roll < self.invalid_rate + self.conflict_rate and self.account_ids:
                # Duplicate of an existing account (exists / exists_with_*).
                a.id = rng.choice(self.account_ids)
                if rng.random() < 0.5:
                    a.user_data_32 ^= 1
            else:
                self.account_ids.append(a.id)
            if rng.random() < self.chain_rate and len(events) < size - 1:
                a.flags = int(a.flags) | int(AccountFlags.linked)
            events.append(a)
        return Operation.create_accounts, events

    def gen_transfers_batch(self, size: int) -> tuple[Operation, list[Transfer]]:
        rng = self.rng
        events: list[Transfer] = []
        batch_created_ids: list[int] = []
        batch_pending: list[int] = []
        while len(events) < size:
            t = Transfer(
                id=self._fresh_id(),
                debit_account_id=self._account_id(),
                credit_account_id=self._account_id(),
                amount=rng.randint(1, 1 << rng.choice((8, 16, 48, 64))),
                ledger=rng.choice(self.ledgers),
                code=rng.randint(1, 100),
                user_data_64=rng.getrandbits(16),
            )
            roll = rng.random()
            if roll < self.two_phase_rate:
                kind = rng.randrange(3)
                if kind == 0:
                    t.flags |= TransferFlags.pending
                    if rng.random() < 0.3:
                        t.timeout = rng.choice((0, 1, 10, 1 << 20))
                    batch_pending.append(t.id)
                    self.pending_ids.append(t.id)
                else:
                    pool = self.pending_ids + batch_pending
                    if pool:
                        t.pending_id = rng.choice(pool)
                        t.flags |= (
                            TransferFlags.post_pending_transfer
                            if kind == 1
                            else TransferFlags.void_pending_transfer
                        )
                        t.debit_account_id = 0
                        t.credit_account_id = 0
                        t.ledger = 0
                        t.code = 0
                        if rng.random() < 0.5:
                            t.amount = 0
            elif roll < self.two_phase_rate + self.balancing_rate:
                t.flags |= rng.choice(
                    (TransferFlags.balancing_debit, TransferFlags.balancing_credit)
                )
                if rng.random() < 0.3:
                    t.amount = 0
            elif roll < self.two_phase_rate + self.balancing_rate + self.invalid_rate:
                mutation = rng.randrange(10)
                if mutation == 0:
                    t.id = 0
                elif mutation == 1:
                    t.id = U128_MAX
                elif mutation == 2:
                    t.debit_account_id = self._fresh_id()  # not found
                elif mutation == 3:
                    t.credit_account_id = 0
                elif mutation == 4:
                    t.credit_account_id = t.debit_account_id
                elif mutation == 5:
                    t.amount = 0
                elif mutation == 6:
                    t.ledger = 0
                elif mutation == 7:
                    t.code = 0
                elif mutation == 8:
                    t.flags = int(t.flags) | (1 << rng.randint(6, 15))
                elif mutation == 9:
                    t.timeout = 5  # timeout without pending
            elif (
                roll < self.two_phase_rate + self.balancing_rate
                + self.invalid_rate + self.conflict_rate
            ):
                pool = self.transfer_ids + batch_created_ids
                if pool:
                    t.id = rng.choice(pool)  # duplicate id (exists checks)
                    if rng.random() < 0.3:
                        t.amount += 1

            if rng.random() < self.chain_rate and len(events) < size - 1:
                t.flags = int(t.flags) | int(TransferFlags.linked)
            if t.id not in batch_created_ids:
                batch_created_ids.append(t.id)
                self.transfer_ids.append(t.id)
            events.append(t)
        return Operation.create_transfers, events

    def gen_lookup_batch(self, size: int, kind: str) -> tuple[Operation, list[int]]:
        rng = self.rng
        pool = self.account_ids if kind == "accounts" else self.transfer_ids
        ids = [
            rng.choice(pool) if pool and rng.random() > 0.2 else self._fresh_id()
            for _ in range(size)
        ]
        op = (
            Operation.lookup_accounts
            if kind == "accounts"
            else Operation.lookup_transfers
        )
        return op, ids
