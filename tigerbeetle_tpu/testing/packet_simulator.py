"""Virtual network with seeded faults: delay, loss, duplication, reorder,
partitions (reference: src/testing/packet_simulator.zig:79 — delay, loss,
replay, clogging, 5 partition modes).

Deterministic: a seed fixes every decision; messages deliver on virtual
ticks through a priority queue ordered by (deliver_tick, sequence), so the
same seed always produces the same interleaving.
"""

from __future__ import annotations

import heapq
import random

from tigerbeetle_tpu.io.network import Address, Handler, Network


PARTITION_MODES = (
    "uniform_size",      # a random minority side is cut off
    "uniform_partition",  # every replica independently coin-flipped to a side
    "isolate_single",    # one replica cut from everyone
    "single_link",       # one replica pair's link cut
    "clog_link",         # one link CLOGGED: packets massively delayed, not
                         # dropped — bursts of stale traffic on heal
)


class PacketSimulatorOptions:
    def __init__(
        self,
        one_way_delay_min: int = 1,
        one_way_delay_max: int = 4,
        packet_loss_probability: float = 0.0,
        packet_replay_probability: float = 0.0,
        partition_probability: float = 0.0,  # per tick: start a partition
        unpartition_probability: float = 0.2,  # per tick: heal it
        partition_modes: tuple = PARTITION_MODES,
        partition_symmetry_probability: float = 0.7,  # else one-way cut
        client_loss_probability: float = 0.0,
        client_replay_probability: float = 0.0,
    ):
        self.one_way_delay_min = one_way_delay_min
        self.one_way_delay_max = one_way_delay_max
        self.packet_loss_probability = packet_loss_probability
        self.packet_replay_probability = packet_replay_probability
        self.partition_probability = partition_probability
        self.unpartition_probability = unpartition_probability
        self.partition_modes = partition_modes
        self.partition_symmetry_probability = partition_symmetry_probability
        # Client-link fault dial (ADDITIVE to the general loss/replay):
        # frames with a client endpoint — requests, replies, busy sheds,
        # evictions, pings — drop or duplicate at their own rate, so the
        # client runtime's timeout/retarget/dedup transitions get
        # exercised without destabilizing the consensus links. Zero (the
        # default) draws nothing from the rng: pre-existing seeds replay
        # byte-identically.
        self.client_loss_probability = client_loss_probability
        self.client_replay_probability = client_replay_probability


class PacketSimulator(Network):
    def __init__(self, seed: int, replica_count: int,
                 options: PacketSimulatorOptions | None = None):
        self.rng = random.Random(seed)
        self.replica_count = replica_count
        self.options = options or PacketSimulatorOptions()
        self.handlers: dict[Address, Handler] = {}
        self.queue: list[tuple[int, int, Address, Address, bytes]] = []
        self._seq = 0
        self.tick_now = 0
        # partition: a set of replicas isolated from the rest (clients count
        # as being on the majority side)
        self.partition: set[int] = set()
        # one-way cut replica links (src, dst) — the generalized form the
        # reference's partition modes/symmetries reduce to (reference:
        # src/testing/packet_simulator.zig:79)
        self.partition_links: set[tuple[int, int]] = set()
        # clogged links: packets still deliver, tens of ticks late (the
        # reference's clogging — stale bursts arrive after the heal)
        self.clogged_links: set[tuple[int, int]] = set()
        self.crashed: set[int] = set()
        self.stats = {"sent": 0, "delivered": 0, "lost": 0, "replayed": 0,
                      "partitioned_drops": 0}

    def attach(self, addr: Address, handler: Handler) -> None:
        self.handlers[addr] = handler

    # -- faults --

    def _is_replica(self, addr: Address) -> bool:
        return 0 <= addr < self.replica_count

    def _cut(self, src: Address, dst: Address) -> bool:
        if src in self.crashed or dst in self.crashed:
            return True
        if self.partition:
            a = src in self.partition if self._is_replica(src) else False
            b = dst in self.partition if self._is_replica(dst) else False
            if a != b:  # across the partition boundary
                return True
        if self.partition_links and self._is_replica(src) and self._is_replica(dst):
            return (src, dst) in self.partition_links
        return False

    def clear_partitions(self) -> None:
        self.partition = set()
        self.partition_links = set()
        self.clogged_links = set()

    def step_partitions(self) -> None:
        o = self.options
        if self.partition or self.partition_links or self.clogged_links:
            if self.rng.random() < o.unpartition_probability:
                self.clear_partitions()
            return
        if not (o.partition_probability > 0
                and self.rng.random() < o.partition_probability):
            return
        if self.replica_count < 2:
            return  # single-replica cluster: no links to cut (VOPR r1 draw)
        mode = self.rng.choice(list(o.partition_modes))
        symmetric = self.rng.random() < o.partition_symmetry_probability
        n = self.replica_count
        if mode == "isolate_single":
            side = {self.rng.randrange(n)}
        elif mode == "single_link":
            a, b = self.rng.sample(range(n), 2)
            self.partition_links.add((a, b))
            if symmetric:
                self.partition_links.add((b, a))
            return
        elif mode == "clog_link":
            a, b = self.rng.sample(range(n), 2)
            self.clogged_links.add((a, b))
            if symmetric:
                self.clogged_links.add((b, a))
            return
        elif mode == "uniform_partition":
            # independent coin flip per replica; both sides may be any size
            # (including empty — then nothing is cut, a valid draw)
            side = {r for r in range(n) if self.rng.random() < 0.5}
            if len(side) == n:
                side = set()
        else:  # uniform_size: a random minority
            k = self.rng.randint(1, max(1, (n - 1) // 2))
            side = set(self.rng.sample(range(n), k))
        if symmetric:
            self.partition = side
            return
        # asymmetric: the side can send OUT but hears nothing back
        for s in range(n):
            for d in range(n):
                if s == d:
                    continue
                if (s not in side) and (d in side):
                    self.partition_links.add((s, d))

    # -- transport --

    def send(self, src: Address, dst: Address, data: bytes) -> None:
        self.stats["sent"] += 1
        o = self.options
        if self._cut(src, dst):
            self.stats["partitioned_drops"] += 1
            return
        if o.packet_loss_probability and self.rng.random() < o.packet_loss_probability:
            self.stats["lost"] += 1
            return
        client_link = not (self._is_replica(src) and self._is_replica(dst))
        if (
            client_link
            and o.client_loss_probability
            and self.rng.random() < o.client_loss_probability
        ):
            self.stats["client_lost"] = self.stats.get("client_lost", 0) + 1
            return
        copies = 1
        if o.packet_replay_probability and self.rng.random() < o.packet_replay_probability:
            copies = 2
            self.stats["replayed"] += 1
        if (
            client_link
            and copies == 1
            and o.client_replay_probability
            and self.rng.random() < o.client_replay_probability
        ):
            copies = 2
            self.stats["client_replayed"] = (
                self.stats.get("client_replayed", 0) + 1
            )
        clogged = (
            self._is_replica(src) and self._is_replica(dst)
            and (src, dst) in self.clogged_links
        )
        for _ in range(copies):
            delay = self.rng.randint(o.one_way_delay_min, o.one_way_delay_max)
            if clogged:  # stale burst: arrives long after the clog heals
                delay += self.rng.randint(30, 80)
                self.stats["clogged"] = self.stats.get("clogged", 0) + 1
            self._seq += 1
            heapq.heappush(
                self.queue,
                (self.tick_now + delay, self._seq, src, dst, bytes(data)),
            )

    def tick(self) -> int:
        """Advance one tick; deliver everything due. Handlers may send more
        (those land on later ticks). Returns messages delivered."""
        self.tick_now += 1
        self.step_partitions()
        n = 0
        while self.queue and self.queue[0][0] <= self.tick_now:
            _, _, src, dst, data = heapq.heappop(self.queue)
            if self._cut(src, dst):
                self.stats["partitioned_drops"] += 1
                continue
            handler = self.handlers.get(dst)
            if handler is None:
                continue
            self.stats["delivered"] += 1
            handler(src, data)
            n += 1
        return n
