"""Deterministic in-process cluster: real replicas + clients over fakes.

The reference's ClusterType (reference: src/testing/cluster.zig:50-73)
wires production replicas to in-memory Storage, a virtual Network, and
virtual Time with ZERO changes to the replica code — the comptime seams.
This is the same harness over our seams, used by the cluster tests and the
simulator.
"""

from __future__ import annotations

from tigerbeetle_tpu.constants import ConfigCluster, ConfigProcess
from tigerbeetle_tpu.io.network import InProcessNetwork
from tigerbeetle_tpu.io.storage import MemoryStorage, ZoneLayout
from tigerbeetle_tpu.io.time import DeterministicTime
from tigerbeetle_tpu.types import Operation
from tigerbeetle_tpu.vsr.client import Client
from tigerbeetle_tpu.vsr.durable import format_data_file
from tigerbeetle_tpu.vsr.header import Header
from tigerbeetle_tpu.vsr.replica import Replica

CLIENT_ID_BASE = 1 << 64  # client addresses: above any replica index


class Cluster:
    def __init__(
        self,
        replica_count: int = 3,
        cluster: ConfigCluster | None = None,
        process: ConfigProcess | None = None,
        grid_size: int = 8 * 1024 * 1024,
        mode: str = "auto",
        backend_factory=None,
        network: InProcessNetwork | None = None,
        seed: int = 0,
        forest_blocks: int = 0,
        standby_count: int = 0,
        metrics=None,
        tracer=None,
        tracer_factory=None,
    ):
        from tigerbeetle_tpu.constants import TEST_CLUSTER, TEST_PROCESS

        self.cluster_config = cluster or TEST_CLUSTER
        self.process_config = process or TEST_PROCESS
        self.network = network if network is not None else InProcessNetwork()
        self.time = DeterministicTime()
        self.mode = mode
        self.backend_factory = backend_factory
        self.layout = ZoneLayout(self.cluster_config, grid_size=grid_size,
                                 forest_blocks=forest_blocks)
        self.storages = []
        self.replicas: list[Replica] = []
        self.clients: list[Client] = []
        self.detached: set[int] = set()
        self.network.filters.append(
            lambda src, dst, data: src not in self.detached
            and dst not in self.detached
        )

        self.standby_count = standby_count
        self.replica_count = replica_count  # ACTIVE replicas only
        for i in range(replica_count + standby_count):
            storage = MemoryStorage(self.layout, seed=seed * 97 + i)
            format_data_file(storage, self.cluster_config)
            self.storages.append(storage)
            r = Replica(
                i, replica_count, storage, self.network, self.time,
                self.cluster_config, self.process_config, mode=mode,
                backend_factory=backend_factory,
                standby_count=standby_count,
                # observability pass-through: a harness can hand every
                # replica one shared registry/tracer (tests do), or a
                # tracer PER replica via tracer_factory(i) — the shape
                # the cluster-causal stitch tests use (pid = index)
                metrics=metrics,
                tracer=tracer_factory(i) if tracer_factory else tracer,
            )
            # thread timing must not leak into deterministic runs
            r.sync_payload_async = False
            r.open()
            self.replicas.append(r)

    def add_client(self) -> Client:
        c = Client(
            CLIENT_ID_BASE + len(self.clients), self.network,
            self.replica_count,
        )
        self.clients.append(c)
        c.register()
        self.network.run()
        c.take_reply()
        assert c.session != 0
        return c

    def execute(self, client: Client, operation: Operation,
                body: bytes) -> tuple[Header, bytes]:
        """Send one request and pump the network until its reply arrives.
        One broadcast retry models the client's request timeout (it may not
        know the current primary after a view change)."""
        client.request(operation, body)
        self.network.run()
        if client.reply is None:
            client.resend()
            self.network.run()
        return client.take_reply()

    def run_ticks(self, n: int) -> None:
        """Advance virtual time: each tick every replica ticks, then the
        network quiesces (the simulator interleaves these differently)."""
        for _ in range(n):
            self.time.tick()
            for r in self.replicas:
                if r.replica not in self.detached:
                    r.tick()
            self.network.run()

    def detach_replica(self, index: int) -> None:
        """Crash a replica: no messages in or out, no ticks."""
        self.detached.add(index)

    def reattach_replica(self, index: int) -> None:
        self.detached.discard(index)

    def restart_replica(self, index: int, backend_factory=None) -> Replica:
        """Crash-restart a replica over its surviving storage bytes."""
        old = self.replicas[index]
        r = Replica(
            index, self.replica_count, self.storages[index], self.network,
            self.time, self.cluster_config, self.process_config,
            mode=self.mode,
            backend_factory=backend_factory or self.backend_factory,
            standby_count=self.standby_count,
        )
        r.sync_payload_async = False  # deterministic harness
        r.open()
        self.replicas[index] = r
        self.detached.discard(index)
        del old
        # Recovering replicas rejoin via request_start_view -> start_view;
        # pump until the handshake settles (ticks drive retries if needed).
        self.network.run()
        for _ in range(3 * 40):
            if r.status == "normal":
                break
            self.run_ticks(1)
        return r
