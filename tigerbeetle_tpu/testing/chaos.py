"""Live-cluster chaos harness: real processes, real sockets, real faults.

Everything the in-process simulator proves under virtual time, this
proves against the PRODUCTION stack: a real N-replica TCP cluster
(`tigerbeetle_tpu start` processes) under a multiplexed client fleet
driven purely by the fault-tolerant client runtime (vsr/client.py tick
state machine — the harness only pumps buses and ticks clients; no
hand-rolled retry anywhere), while faults land on live processes:

- SIGKILL + restart of the primary and of backups (crash-failover);
- SIGSTOP/SIGCONT gray failures: the process is alive, holds its
  sockets, answers nothing — the failure mode timeouts exist for;
- connection resets (SO_LINGER=0 closes): every client link dies at
  once and must re-dial + re-alias without driver help;
- a disk-fault flip on one replica's restart: WAL bytes corrupted while
  the process is down, recovery must classify + repair from peers.

Verification is end-to-end and three-way (the reference VOPR's
liveness/safety checkers, over the wire):

- zero LOST transfers: every batch a client submitted is acked (the
  fleet drives until its whole queue drains; typed client errors
  surface instead of hanging);
- zero DUPLICATED transfers: wire conservation (debits_posted ==
  credits_posted == acked events, each transfer moves amount=1) plus
  the CDC stream's unique transfer ids and all-ok result codes — a
  double-executed batch would surface as id-exists result codes;
- CDC stream parity: replica 0 streams `--cdc-jsonl` with a durable
  cursor across its own crashes; the deduped stream must carry exactly
  the acked transfers;
- hash-log parity (dual backend): each replica's graceful shutdown
  verifies its device applier bit-exact against the native engine
  (per-op hash-log rings name the first divergent op if any).

The recovery metric is time-to-first-commit-after-kill: wall ms from
the fault to the first client reply that lands afterwards (a reply
requires a live primary — served fresh or from the replicated client
table, either way the cluster re-formed).
"""

from __future__ import annotations

import json
import os
import random
import signal
import subprocess
import sys
import threading
import time
from collections import deque

import numpy as np

from tigerbeetle_tpu.benchmark import (
    REPO,
    _accounts_body,
    _transfers_body,
    free_port,
    kill_process_group,
)
from tigerbeetle_tpu.constants import ConfigCluster
from tigerbeetle_tpu.io.storage import Zone, ZoneLayout
from tigerbeetle_tpu.metrics import Metrics
from tigerbeetle_tpu.prodday import RecoveryProbe
from tigerbeetle_tpu.types import Operation
from tigerbeetle_tpu.vsr.client import Client, WallTicker

CHAOS_ACTIONS = (
    "kill_primary", "kill_backup", "gray_primary", "reset_conns",
)


def inject_wal_fault(path: str, cluster_cfg: ConfigCluster,
                     rng: random.Random, slots: int = 4) -> list[int]:
    """Flip bytes inside a few WAL prepare slots of a DOWN replica's data
    file (the disk-fault restart flip): XOR 0xFF over 64 bytes mid-body,
    so whatever the slot held — a prepare or padding — reads back
    corrupt. Recovery must classify the slots faulty and repair from
    peers (never trust, never wedge). Returns the slots flipped."""
    layout = ZoneLayout(cluster_cfg)
    msg_max = cluster_cfg.message_size_max
    hit = sorted(rng.sample(range(cluster_cfg.journal_slot_count), slots))
    with open(path, "r+b") as f:
        for slot in hit:
            off = layout.offset(Zone.wal_prepares, slot * msg_max + 256)
            f.seek(off)
            buf = bytes(b ^ 0xFF for b in f.read(64))
            f.seek(off)
            f.write(buf)
    return hit


class ChaosServer:
    """One replica process: spawn / SIGKILL / SIGSTOP / SIGCONT /
    graceful terminate, stdout drained on a daemon thread with the
    shutdown [stats] line captured per incarnation."""

    def __init__(self, index: int, addresses: str, path: str, env: dict,
                 backend: str, session_args: tuple, extra_args: tuple,
                 log):
        self.index = index
        self.addresses = addresses
        self.path = path
        self.env = env
        self.backend = backend
        self.session_args = session_args
        self.extra_args = extra_args
        self.log = log
        self.proc: subprocess.Popen | None = None
        self.stats: dict = {}  # last incarnation's [stats] payload
        self.ready = threading.Event()
        self.spawns = 0
        self.stopped = False  # SIGSTOPped (gray failure)

    def spawn(self, wait: bool = True, boot_timeout_s: float = 300.0) -> None:
        assert self.proc is None or self.proc.poll() is not None
        self.spawns += 1
        self.stats = {}
        self.stopped = False
        self.ready.clear()
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "tigerbeetle_tpu", "start",
             "--addresses", self.addresses,
             "--replica", str(self.index),
             "--backend", self.backend,
             *self.session_args, *self.extra_args, self.path],
            cwd=REPO, env=self.env, start_new_session=True,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        stats, ready = self.stats, self.ready

        def _boot_then_drain(pipe=self.proc.stdout, idx=self.index):
            # boot phase (until "listening"), then drain until EOF: one
            # thread per incarnation, so a mid-run RESTART never blocks
            # the drive loop on a readline while the fleet needs pumping
            for out in pipe:
                line = out.rstrip()
                if "listening" in line:
                    ready.set()
                elif line.startswith("[stats] "):
                    try:
                        stats.update(json.loads(line[8:]))
                    except ValueError:
                        pass
                else:
                    self.log(f"[r{idx}]", line)

        threading.Thread(target=_boot_then_drain, daemon=True).start()
        if wait:
            if not self.ready.wait(boot_timeout_s):
                raise TimeoutError(
                    f"chaos replica {self.index} never reached listening"
                )

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def kill(self) -> None:
        """SIGKILL the whole process group: no shutdown path runs (the
        crash the WAL + replicated client table exist for)."""
        assert self.alive
        kill_process_group(self.proc)
        self.proc.wait()

    def sigstop(self) -> None:
        """Gray failure: alive, sockets open, answering nothing."""
        assert self.alive and not self.stopped
        os.killpg(self.proc.pid, signal.SIGSTOP)
        self.stopped = True

    def sigcont(self) -> None:
        if self.proc is not None and self.stopped:
            try:
                os.killpg(self.proc.pid, signal.SIGCONT)
            except (ProcessLookupError, OSError):
                pass
            self.stopped = False

    def terminate(self, timeout_s: float = 650.0) -> dict:
        """Graceful SIGTERM: the server prints [stats] (dual mode runs
        its device-parity verification inside it) and exits."""
        if self.proc is None:
            return self.stats
        self.sigcont()
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                pass
        # the drain thread sees EOF once the process exits; give it a
        # beat to finish parsing the [stats] line it may still hold
        for _ in range(50):
            if self.stats:
                break
            time.sleep(0.1)
        kill_process_group(self.proc)
        return self.stats


class _Session:
    """One logical session: a runtime-driven Client plus its share of
    the workload queue. NO retry logic lives here — backoff, re-target,
    busy handling and failover are all Client.tick()."""

    __slots__ = ("client", "ticker", "queue", "events_inflight", "acked",
                 "issue_seq")

    def __init__(self, client: Client, tick_s: float):
        self.client = client
        self.ticker = WallTicker(client, tick_s=tick_s)
        self.queue: list[bytes] = []
        self.events_inflight = 0
        self.acked = 0
        self.issue_seq = 0  # fleet._issue_seq when the batch was issued


class ChaosFleet:
    """n_sessions logical sessions multiplexed over `conns` demux TCP
    buses against the cluster, all on the client runtime."""

    CLIENT_BASE = 0xCA05_0000

    def __init__(self, ports: list[int], n_sessions: int, conns: int,
                 metrics: Metrics, tick_s: float = 0.01,
                 request_timeout_ticks: int = 40):
        from tigerbeetle_tpu.io.message_bus import TCPMessageBus

        addresses = [("127.0.0.1", p) for p in ports]
        self.replica_count = len(ports)
        self.buses = [
            TCPMessageBus(addresses, 0xCAFE_0000 + b, demux=True)
            for b in range(conns)
        ]
        for b in self.buses:
            b.metrics = metrics
        self.sessions = [
            _Session(
                Client(
                    self.CLIENT_BASE + i, self.buses[i % conns],
                    replica_count=self.replica_count,
                    request_timeout_ticks=request_timeout_ticks,
                    # live failover wants a snappy capped ladder (400ms
                    # base at 10ms ticks, 4x cap); the deeper default
                    # ladder is for polite steady-state retries
                    max_backoff_exponent=2,
                    ping_ticks=200,
                    metrics=metrics,
                ),
                tick_s,
            )
            for i in range(n_sessions)
        ]
        self.acked_events = 0
        self.total_events = 0
        self.max_op = 0  # highest committed op any reply named
        self._h_recovery = metrics.histogram("chaos.recovery_ms", unit="ms")
        self._issue_seq = 0  # requests issued (stamps _Session.issue_seq)
        self.errors: list[str] = []
        # (monotonic, events) per acked batch — the failover bench
        # derives before/after-kill throughput windows from it
        self.acked_timeline: list[tuple[float, int]] = []
        # Recovery probe (tigerbeetle_tpu/prodday.py RecoveryProbe —
        # the same arithmetic scores the prodday recovery SLO): armed at
        # fault time, resolved by the first reply that PROVES post-fault
        # service. recoveries_ms aliases the probe's list (appended in
        # place, never rebound) so existing readers keep working.
        self.recovery = RecoveryProbe(self._h_recovery)
        self.recoveries_ms = self.recovery.recoveries_ms

    def pump(self) -> int:
        n = 0
        for b in self.buses:
            n += b.pump(timeout=0.0)
        return n

    def mark_fault(self, now: float) -> None:
        """Arm the time-to-first-commit-after-fault probe."""
        self.recovery.arm(now, self.view, self._issue_seq)

    def step(self, now: float) -> int:
        """One drive turn: pump, tick, harvest replies, feed queues.
        Returns replies harvested (0 = idle turn, caller may sleep)."""
        dispatched = self.pump()
        harvested = 0
        for s in self.sessions:
            s.ticker.advance(now)
            c = s.client
            try:
                c.poll()
            except Exception as e:  # typed errors: record, never hang
                self.errors.append(f"{type(e).__name__}: {e}")
                s.events_inflight = 0
            if c.reply is not None:
                _h, body = c.take_reply()
                self.max_op = max(self.max_op, _h.op)
                if body != b"":
                    self.errors.append(
                        f"client {c.client_id:#x}: non-empty reply "
                        f"({len(body)} bytes of result structs)"
                    )
                t = time.monotonic()
                self.recovery.observe_reply(t, _h.view, s.issue_seq)
                self.acked_events += s.events_inflight
                self.acked_timeline.append((t, s.events_inflight))
                s.acked += s.events_inflight
                s.events_inflight = 0
                harvested += 1
            if c.in_flight is None and c.session != 0 and s.queue:
                body = s.queue.pop(0)
                s.events_inflight = len(body) // 128
                self._issue_seq += 1
                s.issue_seq = self._issue_seq
                c.request(Operation.create_transfers, body)
        return harvested + dispatched

    def outstanding(self) -> int:
        return self.total_events - self.acked_events

    @property
    def view(self) -> int:
        return max(s.client.view for s in self.sessions)

    def register_all(self, deadline_s: float = 300.0,
                     window: int = 64) -> float:
        """Windowed registration storm: every register is a consensus op
        against a bounded pipeline, so at most `window` are in flight
        (the runtime's timeouts still cover any the replica dropped)."""
        t0 = time.monotonic()
        pending = deque(self.sessions)
        active: list[_Session] = []
        while pending or active:
            now = time.monotonic()
            if now - t0 > deadline_s:
                raise TimeoutError(
                    f"registration stalled: {len(pending)} pending "
                    f"{len(active)} active"
                )
            while pending and len(active) < window:
                s = pending.popleft()
                s.client.register()
                active.append(s)
            n = self.pump()
            still = []
            for s in active:
                s.ticker.advance(now)
                s.client.poll()
                if s.client.reply is not None:
                    s.client.take_reply()
                if s.client.session == 0:
                    still.append(s)
            active = still
            if n == 0:
                time.sleep(0.0005)
        return time.monotonic() - t0

    def execute(self, session: _Session, operation: Operation,
                body: bytes, deadline_s: float = 120.0) -> bytes:
        """One synchronous request through a session (setup/verification
        traffic — the runtime still owns retries)."""
        c = session.client
        c.request(operation, body)
        t0 = time.monotonic()
        while not c.done:
            now = time.monotonic()
            if now - t0 > deadline_s:
                raise TimeoutError(f"request stalled ({operation})")
            if self.pump() == 0:
                time.sleep(0.0005)
            session.ticker.advance(now)
        _h, reply = c.take_reply()
        self.max_op = max(self.max_op, _h.op)
        return reply

    def close(self) -> None:
        for b in self.buses:
            try:
                b.sel.close()
            except Exception:
                pass


def _parse_cdc_stream(path: str) -> dict:
    """Deduped view of the chaos run's CDC JSONL: at-least-once becomes
    exactly-once by keeping each (op, ix) record's FIRST delivery (the
    same dedup every consumer applies). A torn TRAILING line (SIGKILL
    mid-write) is tolerated — only the tail can tear in an append-only
    single-writer file; its op is unacked and redelivered."""
    seen: set[tuple[int, int]] = set()
    ids_seen: set[int] = set()
    transfers_ok = 0
    transfers_bad = 0
    redelivered = 0
    dup_ids = 0
    lines = 0
    with open(path) as f:
        raw = f.read().splitlines()
    for i, line in enumerate(raw):
        try:
            rec = json.loads(line)
        except ValueError:
            if i == len(raw) - 1:
                break
            raise
        lines += 1
        if rec.get("kind") != "transfer":
            continue
        key = (rec["op"], rec.get("ix", 0))
        if key in seen:
            redelivered += 1
            continue
        seen.add(key)
        tid = rec.get("id")
        if tid in ids_seen:
            # the same transfer id committed under TWO ops: a request
            # executed twice — exactly the bug class the harness hunts
            dup_ids += 1
            continue
        ids_seen.add(tid)
        if rec.get("result") == 0:
            transfers_ok += 1
        else:
            transfers_bad += 1
    return {
        "lines": lines,
        "transfers_ok": transfers_ok,
        "transfers_bad": transfers_bad,
        "unique_ids": len(ids_seen),
        "redelivered_records": redelivered,
        "dup_ids": dup_ids,
    }


def run_chaos(
    n_sessions: int = 64,
    conns: int = 4,
    n_accounts: int = 128,
    events_per_batch: int = 16,
    batches_per_session: int = 6,
    replica_count: int = 3,
    backend: str = "native",
    faults: tuple = ("kill_primary",),
    restart_after_s: float = 2.0,
    gray_s: float = 3.0,
    disk_fault_on_restart: bool = True,
    reply_slots: int = 64,
    seed: int = 1,
    jax_platform: str | None = "cpu",
    deadline_s: float = 600.0,
    settle_s: float = 1.0,
    ingress: bool = False,
    tmpdir: str | None = None,
    strict_stream: bool = True,
    log=None,
) -> dict:
    """The live chaos run. `faults` is an ordered tuple of CHAOS_ACTIONS
    fired at evenly spaced acked-progress points of the workload:

      kill_primary | kill_backup — SIGKILL (auto-restart after
          `restart_after_s`; the FIRST restart flips WAL disk bytes when
          disk_fault_on_restart);
      gray_primary               — SIGSTOP for `gray_s`, then SIGCONT;
      reset_conns                — RST every client connection.

    Returns the verification report; raises on any lost/duplicated
    transfer, CDC drift, or parity failure."""
    import tempfile

    log = log or (lambda *_: None)
    rng = random.Random(seed)
    own_tmp = tmpdir is None
    if own_tmp:
        tmp = tempfile.TemporaryDirectory(prefix="tb_chaos_")
        tmpdir = tmp.name

    ports = [free_port() for _ in range(replica_count)]
    addresses = ",".join(f"127.0.0.1:{p}" for p in ports)
    clients_max = n_sessions + 64
    session_args = (
        "--clients-max", str(clients_max),
        "--client-reply-slots", str(reply_slots),
    )
    cluster_cfg = ConfigCluster(
        replica_count=replica_count,
        clients_max=clients_max,
        client_reply_slots=reply_slots,
    )
    pp = os.environ.get("PYTHONPATH", "")
    env = dict(os.environ, PYTHONPATH=f"{REPO}:{pp}" if pp else REPO,
               TB_PARENT_WATCHDOG="1")
    if jax_platform:
        env["TB_JAX_PLATFORM"] = jax_platform

    # ledger slots sized to the workload (the server defaults allocate
    # 2^24 transfer slots — three dual-backend replicas on one box would
    # fight for memory before the first fault lands)
    total_events = n_sessions * batches_per_session * events_per_batch
    slots_log2 = 14
    while total_events * 2 + 4096 > (1 << slots_log2) // 2:
        slots_log2 += 1
    acct_log2 = max(14, (n_accounts * 2 + 2).bit_length())
    start_args = session_args + (
        "--account-slots-log2", str(acct_log2),
        "--transfer-slots-log2", str(slots_log2),
    )

    servers: list[ChaosServer] = []
    paths: list[str] = []
    for i in range(replica_count):
        path = os.path.join(tmpdir, f"chaos_{i}.tigerbeetle")
        paths.append(path)
        fmt = subprocess.run(
            [sys.executable, "-m", "tigerbeetle_tpu", "format",
             "--cluster", "7", "--replica", str(i),
             "--replica-count", str(replica_count),
             *session_args, path],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
        )
        assert fmt.returncode == 0, fmt.stderr
    cdc_path = os.path.join(tmpdir, "chaos_cdc.jsonl")
    for i in range(replica_count):
        extra: tuple = ("--ingress",) if ingress else ()
        if i == 0:
            # CDC rides replica 0 ACROSS its crashes: the durable cursor
            # makes each incarnation resume (redeliveries dedup)
            extra = extra + (
                "--cdc-jsonl", cdc_path,
                "--cdc-cursor", cdc_path + ".cursor",
            )
        servers.append(ChaosServer(
            i, addresses, paths[i], env, backend, start_args, extra, log,
        ))

    metrics = Metrics()
    fleet = None
    report: dict = {
        "sessions": n_sessions, "conns": conns, "backend": backend,
        "replicas": replica_count, "faults": list(faults),
        "kills": 0, "restarts": 0, "gray_stops": 0, "conn_resets": 0,
        "disk_fault_slots": [],
    }
    try:
        t0 = time.monotonic()
        for s in servers:
            s.spawn(wait=False)
        for s in servers:
            if not s.ready.wait(300.0):
                raise TimeoutError(f"replica {s.index} never listened")
        log(f"cluster up on {addresses} in {time.monotonic() - t0:.1f}s")

        fleet = ChaosFleet(ports, n_sessions, conns, metrics)
        reg_s = fleet.register_all()
        log(f"{n_sessions} sessions registered in {reg_s:.1f}s")
        report["register_s"] = round(reg_s, 2)

        # accounts + one warm batch through session 0, off the clock
        next_id = 1
        while next_id <= n_accounts:
            k = min(2048, n_accounts - next_id + 1)
            body = fleet.execute(
                fleet.sessions[0], Operation.create_accounts,
                _accounts_body(next_id, k),
            )
            assert body == b"", "account create failed"
            next_id += k
        nrng = np.random.default_rng(seed)
        warm = _transfers_body(nrng, 500_000, events_per_batch, n_accounts)
        assert fleet.execute(
            fleet.sessions[0], Operation.create_transfers, warm,
            deadline_s=600.0,
        ) == b""
        warm_events = events_per_batch

        # per-session workload queues, disjoint id namespaces (unique
        # transfer ids cluster-wide: the CDC duplicate check bites)
        stride = (batches_per_session + 2) * events_per_batch
        for i, s in enumerate(fleet.sessions):
            nid = 1_000_000 + i * stride
            for _ in range(batches_per_session):
                s.queue.append(
                    _transfers_body(nrng, nid, events_per_batch, n_accounts)
                )
                nid += events_per_batch
        fleet.total_events = (
            n_sessions * batches_per_session * events_per_batch
        )

        plan = [
            {"at": (k + 1) / (len(faults) + 1), "action": a, "done": False}
            for k, a in enumerate(faults)
        ]
        pending_restarts: list[list] = []  # [when, server, flip_disk]
        pending_cont: list[list] = []  # [when, server]
        fault_marks: list[tuple[float, str]] = []

        t_drive = time.monotonic()
        log(f"driving {fleet.total_events} transfer events "
            f"across {n_sessions} sessions")
        while fleet.outstanding() > 0:
            now = time.monotonic()
            if now - t_drive > deadline_s:
                raise TimeoutError(
                    f"chaos drive stalled: {fleet.outstanding()} events "
                    f"outstanding, errors={fleet.errors[:4]}"
                )
            if fleet.step(now) == 0:
                time.sleep(0.0005)
            if fleet.errors:
                raise AssertionError(
                    f"typed client errors during chaos: {fleet.errors[:4]}"
                )
            frac = fleet.acked_events / max(1, fleet.total_events)
            for p in plan:
                if p["done"] or frac < p["at"]:
                    continue
                p["done"] = True
                action = p["action"]
                if action in ("kill_primary", "kill_backup"):
                    pi = fleet.view % replica_count
                    idx = pi if action == "kill_primary" else (
                        (pi + 1) % replica_count
                    )
                    victim = servers[idx]
                    if not victim.alive:
                        continue  # already down from an earlier fault
                    victim.sigcont()
                    victim.kill()
                    report["kills"] += 1
                    metrics.counter("chaos.kills").add()
                    now = time.monotonic()
                    fleet.mark_fault(now)
                    fault_marks.append((now, action))
                    log(f"chaos: SIGKILL replica {idx} ({action}) "
                        f"at {frac:.0%} acked")
                    pending_restarts.append([
                        now + restart_after_s, victim,
                        disk_fault_on_restart and report["restarts"] == 0,
                    ])
                elif action == "gray_primary":
                    victim = servers[fleet.view % replica_count]
                    if victim.alive and not victim.stopped:
                        victim.sigstop()
                        report["gray_stops"] += 1
                        metrics.counter("chaos.gray_stops").add()
                        now = time.monotonic()
                        fleet.mark_fault(now)
                        fault_marks.append((now, action))
                        log(f"chaos: SIGSTOP replica {victim.index} "
                            f"at {frac:.0%} acked")
                        pending_cont.append([now + gray_s, victim])
                elif action == "reset_conns":
                    for b in fleet.buses:
                        b.drop_connections()
                    report["conn_resets"] += 1
                    metrics.counter("chaos.conn_resets").add()
                    now = time.monotonic()
                    fleet.mark_fault(now)
                    fault_marks.append((now, action))
                    log(f"chaos: reset every client connection "
                        f"at {frac:.0%} acked")
                else:
                    raise ValueError(f"unknown chaos action {action!r}")
            for entry in list(pending_restarts):
                when, srv, flip = entry
                if now >= when and not srv.alive:
                    pending_restarts.remove(entry)
                    if flip:
                        slots = inject_wal_fault(srv.path, cluster_cfg, rng)
                        report["disk_fault_slots"] = slots
                        log(f"chaos: disk-fault flip on replica "
                            f"{srv.index}'s WAL (slots {slots})")
                    srv.spawn(wait=False)  # boot happens off the loop
                    report["restarts"] += 1
                    metrics.counter("chaos.restarts").add()
                    log(f"chaos: replica {srv.index} restarting")
            for entry in list(pending_cont):
                when, srv = entry
                if now >= when:
                    pending_cont.remove(entry)
                    srv.sigcont()
                    log(f"chaos: SIGCONT replica {srv.index}")
        drive_wall = time.monotonic() - t_drive
        for _w, srv, flip in pending_restarts:  # fault landed at the tail
            # (the workload can drain before restart_after_s elapses —
            # the tail respawn still owes the disk-fault flip)
            if not srv.alive:
                if flip:
                    slots = inject_wal_fault(srv.path, cluster_cfg, rng)
                    report["disk_fault_slots"] = slots
                    log(f"chaos: disk-fault flip on replica "
                        f"{srv.index}'s WAL (slots {slots})")
                srv.spawn(wait=False)
                report["restarts"] += 1
                metrics.counter("chaos.restarts").add()
        for _w, srv in pending_cont:
            srv.sigcont()
        for srv in servers:  # restarted replicas must finish booting
            if srv.proc is not None and srv.alive:
                srv.ready.wait(300.0)
        log(f"workload drained: {fleet.acked_events} events acked in "
            f"{drive_wall:.1f}s; recoveries_ms="
            f"{[round(r) for r in fleet.recoveries_ms]}")

        # settle, then verify conservation over the wire
        time.sleep(settle_s)
        total = fleet.acked_events + warm_events
        from tigerbeetle_tpu.state_machine import decode_accounts, encode_ids

        dpo = cpo = found = 0
        for i in range(0, n_accounts, 8000):
            ids = list(range(1 + i, 1 + min(i + 8000, n_accounts)))
            body = fleet.execute(
                fleet.sessions[0], Operation.lookup_accounts,
                encode_ids(ids),
            )
            arr = decode_accounts(body)
            found += len(arr)
            dpo += int(arr["debits_posted_lo"].sum())
            cpo += int(arr["credits_posted_lo"].sum())
        assert found == n_accounts, (found, n_accounts)
        assert dpo == cpo == total, (
            f"conservation violated: debits={dpo} credits={cpo} "
            f"acked={total} — lost or duplicated transfers"
        )
        log(f"wire conservation verified: {total} transfers")

        # Catch-up barrier: the CDC stream can only carry what replica 0
        # COMMITTED, and a twice-crashed streamer may still be repairing
        # its log from peers — wait for every replica to reach the
        # cluster head (the highest op a client reply named) before the
        # shutdown drain reads the stream's tail.
        from tigerbeetle_tpu.inspect import inspect_live

        target = fleet.max_op
        t_w = time.monotonic()
        for s in servers:
            while True:
                if time.monotonic() - t_w > 300.0:
                    raise TimeoutError(
                        f"replica {s.index} never caught up to op {target}"
                    )
                try:
                    live = inspect_live(
                        "127.0.0.1", ports[s.index], timeout=2.0
                    )
                    if live["commit_min"] >= target:
                        break
                except (OSError, RuntimeError, ValueError):
                    pass  # booting / mid-recovery: poll again
                time.sleep(0.25)
        log(f"all replicas caught up to op {target} "
            f"in {time.monotonic() - t_w:.1f}s")

        # graceful shutdown: parity + the CDC final drain live in SIGTERM
        parity = {}
        for s in servers:
            stats = s.terminate()
            shadow = stats.get("device_shadow") or {}
            parity[f"r{s.index}"] = {
                "verified": shadow.get("verified"),
                "hash_log_ok": (shadow.get("hash_log") or {}).get("ok"),
            }

        cdc = _parse_cdc_stream(cdc_path)
        cdc_error = None
        try:
            assert cdc["dup_ids"] == 0, (
                f"duplicated transfers in CDC: {cdc}"
            )
            assert cdc["transfers_bad"] == 0, (
                f"non-ok transfer results in CDC (double execution?): {cdc}"
            )
            assert cdc["unique_ids"] == total, (
                f"cdc stream drift: {cdc['unique_ids']} unique transfers "
                f"vs {total} acked"
            )
            log(f"cdc stream verified: {cdc['unique_ids']} transfers "
                f"({cdc['redelivered_records']} redelivered records deduped)")
        except AssertionError as e:
            # strict mode (the chaos CLI + tests): a stream-verification
            # failure IS the run's result — raise. The bench failover
            # segment runs strict_stream=False: the wire-conservation
            # check above already proved zero lost/duplicated LEDGER
            # effects, so the measured recovery/tps numbers are valid
            # even when the CDC stream's replay artifacts fail the
            # exactly-once audit — the report then carries BOTH the
            # measurement and the named verification failure instead of
            # nulling the artifact (the r06 lesson).
            if strict_stream:
                raise
            cdc_error = str(e)[:500]
            log(f"cdc stream verification FAILED (reported, not fatal): "
                f"{cdc_error[:200]}")

        if backend in ("dual", "native+device"):
            bad = {
                k: v for k, v in parity.items()
                if not v["verified"] or v["hash_log_ok"] is False
            }
            assert not bad, f"device parity failed after chaos: {bad}"

        # Post-failover throughput ratio from the acked timeline:
        # SYMMETRIC fixed-width windows — the W seconds ending at the
        # first fault vs the W seconds starting at its recovery. (Whole-
        # span averages lie twice: the pre-span starts with the issue
        # burst and the post-span ends with the sparse drain tail.)
        tps_pre = tps_post = None
        if fault_marks and fleet.recoveries_ms and fleet.acked_timeline:
            t_fault = fault_marks[0][0]
            t_rec = t_fault + fleet.recoveries_ms[0] / 1e3
            t_end = fleet.acked_timeline[-1][0]
            w = min(2.0, t_fault - t_drive, max(0.0, t_end - t_rec))
            if w > 0.05:
                tps_pre = sum(
                    n for t, n in fleet.acked_timeline
                    if t_fault - w <= t < t_fault
                ) / w
                tps_post = sum(
                    n for t, n in fleet.acked_timeline
                    if t_rec <= t < t_rec + w
                ) / w

        snap = metrics.snapshot()["counters"]
        report.update({
            "acked_events": fleet.acked_events,
            "lost_events": fleet.outstanding(),
            "wall_s": round(drive_wall, 2),
            "tps": round(fleet.acked_events / drive_wall, 1),
            "failover_recovery_ms": (
                round(fleet.recoveries_ms[0], 1)
                if fleet.recoveries_ms else None
            ),
            "recoveries_ms": [round(r, 1) for r in fleet.recoveries_ms],
            "tps_pre_fault": round(tps_pre, 1) if tps_pre else None,
            "tps_post_recovery": round(tps_post, 1) if tps_post else None,
            "post_failover_tps_ratio": (
                round(tps_post / tps_pre, 3) if tps_pre and tps_post
                else None
            ),
            "conservation_ok": True,
            "cdc_ok": cdc_error is None,
            "verification_error": cdc_error,
            "cdc": cdc,
            "parity": parity,
            "client": {
                k.split(".", 1)[1]: v for k, v in snap.items()
                if k.startswith("client.")
            },
            "bus_reconnects": snap.get("bus.reconnects", 0),
            "bus_dial_failures": snap.get("bus.dial_failures", 0),
        })
        return report
    finally:
        if fleet is not None:
            fleet.close()
        for s in servers:
            s.sigcont()
            if s.proc is not None:
                kill_process_group(s.proc)
        if own_tmp:
            tmp.cleanup()


def run_failover(
    n_sessions: int = 64,
    conns: int = 4,
    events_per_batch: int = 64,
    batches_per_session: int = 10,
    backend: str = "native",
    **kw,
) -> dict:
    """The bench `failover` segment: one SIGKILL of the primary mid-run;
    reports failover_recovery_ms and post_failover_tps_ratio (acked-event
    rate after recovery vs before the kill)."""
    return run_chaos(
        n_sessions=n_sessions, conns=conns,
        events_per_batch=events_per_batch,
        batches_per_session=batches_per_session,
        backend=backend, faults=("kill_primary",),
        disk_fault_on_restart=False, **kw,
    )
