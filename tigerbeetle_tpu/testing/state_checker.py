"""Cluster correctness checkers (reference:
src/testing/cluster/state_checker.zig:25, storage_checker.zig).

- StateChecker: every replica's committed state is identical (one linear
  history) and matches a model-based oracle replay of the committed ops.
- convergence(): all replicas reached the same commit_min/op/chain head.
"""

from __future__ import annotations

from tigerbeetle_tpu.models.oracle import OracleStateMachine
from tigerbeetle_tpu.state_machine import StateMachine
from tigerbeetle_tpu.types import Operation


def assert_convergence(replicas) -> None:
    heads = {(r.commit_min, r.op, r.parent_checksum) for r in replicas}
    assert len(heads) == 1, f"replicas diverged: {heads}"


def assert_identical_state(replicas) -> None:
    """Bit-exact state parity across replicas (the reference's
    StorageChecker compares checkpoints byte-for-byte; our state lives in
    the ledger tables — extract() is the canonical view)."""
    base = replicas[0].ledger.extract()
    for r in replicas[1:]:
        other = r.ledger.extract()
        assert other[0] == base[0], f"replica {r.replica}: accounts diverged"
        assert other[1] == base[1], f"replica {r.replica}: transfers diverged"
        assert other[2] == base[2], f"replica {r.replica}: posted diverged"
    tables = {
        tuple(sorted((c, e["session"], e["request"]) for c, e in r.client_table.items()))
        for r in replicas
    }
    assert len(tables) == 1, "client tables diverged"


def assert_matches_oracle(replica, committed: list[tuple[Operation, int, bytes]]):
    """Replay (operation, timestamp, body) through the scalar oracle and
    compare state bit-for-bit with the replica's device ledger."""
    sm = StateMachine(OracleStateMachine(), replica.cluster)
    for operation, timestamp, body in committed:
        if operation in (Operation.create_accounts, Operation.create_transfers):
            sm.commit(operation, timestamp, body)
    oracle = sm.backend
    accounts, transfers, posted = replica.ledger.extract()
    assert accounts == oracle.accounts
    assert transfers == oracle.transfers
    assert posted == oracle.posted
