"""Live federation: real multi-region clusters, real settlement agent.

`run_federation_chaos` is the wall-clock twin of `federation/sim.py`'s
SimFederation, on the production stack: each region is a real N-replica
TCP cluster (`tigerbeetle_tpu start` processes with `--commitment-
interval`, `--federation-region`, and an AOF-backed `--cdc-jsonl` tail
on replica 0), the settlement agent is the SAME sans-IO `SettlementCore`
tailing the region's CDC JSONL file and posting mirror/resolve legs
through the fault-tolerant client runtime, and the region-level fault is
a real SIGKILL of EVERY replica process of one region mid-settlement
(`--kill-cluster` on the chaos CLI) followed by a whole-cluster restart
from disk.

Verification after the storm, all over the wire:

- cross-region conservation per ordered pair: escrow(a->b) posted
  credits on a == mirror posted debits on b == the amounts the harness
  issued toward valid beneficiaries; zero pending escrow residue (the
  void slice came back out);
- commitment-chain audit: each region's CDC JSONL replays through
  `inspect.verify_commitment_stream` (a fresh-oracle StreamVerifier) and
  the recomputed chain head must equal the head the region's replica 0
  published in its shutdown [stats] — the exact check a settlement
  counterparty runs before trusting a region's stream.

The stream tail here is deliberately paranoid about the JSONL file's
at-least-once framing: a SIGKILLed streamer leaves a torn tail line
that the next incarnation's append glues onto (skipped, counted), and
redelivery restarts below the high-water op (the possibly-torn trailing
group is discarded — the redelivery carries it complete). A group is
fed to the core only once a HIGHER op's line proves its emit completed.
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import time
from collections import deque

import numpy as np

from tigerbeetle_tpu import types
from tigerbeetle_tpu.federation.agent import SettlementCore
from tigerbeetle_tpu.federation.topology import (
    FEDERATION_LEDGER,
    SETTLE_CODE,
    FederationTopology,
    escrow_account_id,
    home_account_id,
    mirror_account_id,
    origin_id,
)
from tigerbeetle_tpu.types import (
    CREATE_TRANSFERS_RESULT_DTYPE,
    Account,
    Operation,
    Transfer,
    TransferFlags,
)

HOME_ACCOUNTS = 4  # pinned user accounts per region (matches the sim)
HEARTBEAT_ID_TAG = 0xB0  # heartbeat account id: tag<<120 | region


def _dense_codes(reply_body: bytes, n: int) -> list:
    codes = [0] * n
    if reply_body:
        sparse = np.frombuffer(reply_body, dtype=CREATE_TRANSFERS_RESULT_DTYPE)
        for i, code in zip(sparse["index"], sparse["result"]):
            codes[int(i)] = int(code)
    return codes


class _StreamTail:
    """Incremental reader of a region's CDC JSONL with at-least-once
    framing (module docstring): yields per-op line groups that are
    PROVEN complete — a group is released only when a line of a higher
    op follows it (emission is per-op and file writes preserve order),
    and a redelivery restarting below the current group discards it
    (the redelivery re-carries it complete)."""

    def __init__(self, path: str):
        self.path = path
        self._pos = 0
        self._buf = ""
        self._group: tuple | None = None  # (op, [raw lines])
        self.ready: deque = deque()  # complete groups awaiting the core
        self.torn_lines = 0
        self.discarded_groups = 0

    def poll(self) -> int:
        """Read newly appended bytes; returns complete groups released."""
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                f.seek(self._pos)
                chunk = f.read()
                self._pos = f.tell()
        except FileNotFoundError:
            return 0
        if not chunk:
            return 0
        data = self._buf + chunk
        lines = data.split("\n")
        self._buf = lines.pop()  # trailing partial (or "")
        released = 0
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                # a SIGKILL tore the previous incarnation's tail line and
                # this incarnation's first append glued onto it; the
                # durable cursor redelivers the op intact
                self.torn_lines += 1
                continue
            kind = rec.get("kind")
            # gaps carry a range, not an op; order them at their start
            op = int(rec["from"]) if kind == "gap" else int(rec.get("op", 0))
            if self._group is None:
                self._group = (op, [line])
            elif op == self._group[0]:
                self._group[1].append(line)
            elif op > self._group[0]:
                # a higher op proves the held group's emit completed
                self.ready.append(self._group)
                released += 1
                self._group = (op, [line])
            else:
                # redelivery below the held group: it may be torn —
                # drop it, the redelivery carries it complete
                self.discarded_groups += 1
                self._group = (op, [line])
        return released

    @property
    def held_op(self) -> int:
        """Op of the group awaiting proof-of-completion (0 = none)."""
        return self._group[0] if self._group is not None else 0


class LiveSettlementAgent:
    """One region's settlement agent over the live stack: a
    `SettlementCore` fed from the region's CDC JSONL tail, legs posted
    synchronously through the regions' client fleets (the runtime owns
    retries/failover — a whole-region outage just makes the request
    wait out the restart)."""

    def __init__(self, region: int, topology: FederationTopology,
                 tail: _StreamTail, fleets: list, metrics=None,
                 window: int = 128, request_deadline_s: float = 180.0):
        self.region = region
        self.tail = tail
        self.fleets = fleets
        self.request_deadline_s = request_deadline_s
        self.core = SettlementCore(
            topology, region, window=window, metrics=metrics,
        )
        # settlement lag: committed ops the region's cluster is ahead of
        # the agent's watermark while legs are unfinished (ops, not ms —
        # comparable across rigs and with the sim's bound)
        self.max_lag_ops = 0

    def _create(self, target_region: int, transfers: list) -> list:
        fleet = self.fleets[target_region]
        body = fleet.execute(
            fleet.sessions[1], Operation.create_transfers,
            types.transfers_to_np(transfers).tobytes(),
            deadline_s=self.request_deadline_s,
        )
        return _dense_codes(body, len(transfers))

    def step(self) -> bool:
        """One drive turn: ingest stream groups, push staged legs.
        Returns True when anything moved."""
        progressed = self.tail.poll() > 0
        core = self.core
        while self.tail.ready:
            op, lines = self.tail.ready[0]
            if not core.emit_lines(lines):
                break  # window full: the deque still holds the op
            self.tail.ready.popleft()
            progressed = True
        if core.error is not None:
            raise AssertionError(f"agent r{self.region}: {core.error}")
        if core.pending_count():
            self.max_lag_ops = max(
                self.max_lag_ops,
                self.fleets[self.region].max_op - core.watermark(),
            )
        for dst in sorted(core.dsts_with_work()):
            legs = core.next_mirror_batch(dst, limit=16)
            if not legs:
                continue
            try:
                codes = self._create(dst, core.mirror_transfers(legs))
            except TimeoutError:
                core.on_request_failed(legs)
                raise
            core.on_mirror_replies(legs, codes)
            progressed = True
        legs = core.next_resolve_batch(limit=16)
        if legs:
            try:
                codes = self._create(self.region, core.resolve_transfers(legs))
            except TimeoutError:
                core.on_request_failed(legs)
                raise
            core.on_resolve_replies(legs, codes)
            progressed = True
        return progressed

    def idle(self) -> bool:
        return (
            self.core.idle()
            and not self.tail.ready
        )


def run_federation_chaos(
    regions: int = 2,
    replica_count: int = 3,
    payments: int = 24,
    batch: int = 4,
    commitment_interval: int = 8,
    void_fraction: float = 0.15,
    kill_cluster: bool = True,
    restart_after_s: float = 1.5,
    backend: str = "native",
    seed: int = 1,
    jax_platform: str | None = "cpu",
    deadline_s: float = 600.0,
    settle_deadline_s: float = 300.0,
    tmpdir: str | None = None,
    log=None,
) -> dict:
    """The `--kill-cluster` chaos mode (module docstring). `payments` is
    the number of cross-region origin pendings issued PER region, half
    before and half after the mid-run region kill."""
    import tempfile

    from tigerbeetle_tpu.benchmark import REPO, free_port, kill_process_group
    from tigerbeetle_tpu.inspect import inspect_live, verify_commitment_stream
    from tigerbeetle_tpu.metrics import Metrics
    from tigerbeetle_tpu.state_machine import decode_accounts, encode_ids
    from tigerbeetle_tpu.testing.chaos import ChaosFleet, ChaosServer

    assert regions >= 2, "federation needs at least two regions"
    log = log or (lambda *_: None)
    rng = random.Random(seed)
    own_tmp = tmpdir is None
    if own_tmp:
        tmp = tempfile.TemporaryDirectory(prefix="tb_fed_")
        tmpdir = tmp.name

    topology = FederationTopology.of(regions)
    clients_max = 8
    session_args = ("--clients-max", str(clients_max))
    pp = os.environ.get("PYTHONPATH", "")
    env = dict(os.environ, PYTHONPATH=f"{REPO}:{pp}" if pp else REPO,
               TB_PARENT_WATCHDOG="1")
    if jax_platform:
        env["TB_JAX_PLATFORM"] = jax_platform

    region_ports: list[list[int]] = []
    servers: list[list[ChaosServer]] = []
    cdc_paths: list[str] = []
    fmt_procs = []
    for r in range(regions):
        ports = [free_port() for _ in range(replica_count)]
        region_ports.append(ports)
        addresses = ",".join(f"127.0.0.1:{p}" for p in ports)
        cdc_path = os.path.join(tmpdir, f"region{r}_cdc.jsonl")
        cdc_paths.append(cdc_path)
        row = []
        for i in range(replica_count):
            path = os.path.join(tmpdir, f"region{r}_{i}.tigerbeetle")
            fmt_procs.append(subprocess.Popen(
                [sys.executable, "-m", "tigerbeetle_tpu", "format",
                 "--cluster", str(7000 + r), "--replica", str(i),
                 "--replica-count", str(replica_count),
                 *session_args, path],
                cwd=REPO, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            ))
            extra: tuple = (
                "--account-slots-log2", "14",
                "--transfer-slots-log2", "14",
                "--commitment-interval", str(commitment_interval),
                "--federation-region", str(r),
                "--federation-regions", str(regions),
            )
            if i == 0:
                # the streamed replica: AOF so deep resume never gaps,
                # ack-interval 1 so the JSONL is flushed per op (the
                # live agent tails the file, not a socket)
                extra = extra + (
                    "--aof", os.path.join(tmpdir, f"region{r}.aof"),
                    "--cdc-jsonl", cdc_path,
                    "--cdc-cursor", cdc_path + ".cursor",
                    "--cdc-ack-interval", "1",
                )
            row.append(ChaosServer(
                i, addresses, path, env, backend, session_args, extra,
                lambda *a, _r=r: log(f"[region {_r}]", *a),
            ))
        servers.append(row)
    for p in fmt_procs:
        out, _ = p.communicate(timeout=300)
        assert p.returncode == 0, out

    metrics = Metrics()
    fleets: list[ChaosFleet] = []
    report: dict = {
        "regions": regions, "replicas": replica_count, "backend": backend,
        "payments_per_region": payments, "kills": 0, "restarts": 0,
    }
    t_run = time.monotonic()
    try:
        for row in servers:
            for s in row:
                s.spawn(wait=False)
        for row in servers:
            for s in row:
                if not s.ready.wait(300.0):
                    raise TimeoutError(
                        f"federation replica never listened ({s.path})"
                    )
        log(f"{regions} regions x {replica_count} replicas up in "
            f"{time.monotonic() - t_run:.1f}s")

        # two sessions per region: [0] workload/verification, [1] the
        # settlement write lane (both regions' agents share it — the
        # drive loop is single-threaded, requests are sequential)
        for r in range(regions):
            fleet = ChaosFleet(region_ports[r], 2, 1, metrics)
            fleet.register_all()
            fleets.append(fleet)

        # infrastructure + pinned home accounts, idempotent creates
        for r in range(regions):
            ids = topology.infra_account_ids(r) + [
                home_account_id(r, k, regions) for k in range(HOME_ACCOUNTS)
            ]
            accounts = [
                Account(id=i, ledger=FEDERATION_LEDGER, code=SETTLE_CODE)
                for i in ids
            ]
            body = fleets[r].execute(
                fleets[r].sessions[0], Operation.create_accounts,
                types.accounts_to_np(accounts).tobytes(),
            )
            assert body == b"", f"region {r} bootstrap failed"
        log("federation accounts bootstrapped")

        agents = [
            LiveSettlementAgent(
                r, topology, _StreamTail(cdc_paths[r]), fleets, metrics,
            )
            for r in range(regions)
        ]
        issued_seq = [0] * regions
        # expected POSTED amount per ordered pair (valid beneficiaries
        # only — the void slice must come back out of escrow)
        expected_posted: dict = {}
        issued_amount = 0
        void_targets = 0

        def issue(region: int, count: int) -> None:
            nonlocal issued_amount, void_targets
            fleet = fleets[region]
            left = count
            while left > 0:
                transfers = []
                for _ in range(min(batch, left)):
                    dst = rng.choice(
                        [d for d in range(regions) if d != region]
                    )
                    payer = home_account_id(
                        region, rng.randrange(HOME_ACCOUNTS), regions
                    )
                    void = rng.random() < void_fraction
                    k = (HOME_ACCOUNTS + rng.randrange(4)) if void \
                        else rng.randrange(HOME_ACCOUNTS)
                    beneficiary = home_account_id(dst, k, regions)
                    issued_seq[region] += 1
                    amount = rng.randint(1, 100)
                    issued_amount += amount
                    if void:
                        void_targets += 1
                    else:
                        key = (region, dst)
                        expected_posted[key] = (
                            expected_posted.get(key, 0) + amount
                        )
                    transfers.append(Transfer(
                        id=origin_id(region, issued_seq[region]),
                        debit_account_id=payer,
                        credit_account_id=escrow_account_id(region, dst),
                        amount=amount,
                        ledger=FEDERATION_LEDGER,
                        code=SETTLE_CODE,
                        flags=int(TransferFlags.pending),
                        user_data_128=beneficiary,
                    ))
                body = fleet.execute(
                    fleet.sessions[0], Operation.create_transfers,
                    types.transfers_to_np(transfers).tobytes(),
                )
                assert body == b"", (
                    f"origin pending rejected on region {region}"
                )
                left -= len(transfers)

        def heartbeat(region: int) -> None:
            """Commit a no-op op so the stream advances past the tail's
            held group (idempotent duplicate create; `exists` is fine)."""
            fleets[region].execute(
                fleets[region].sessions[0], Operation.create_accounts,
                types.accounts_to_np([Account(
                    id=(HEARTBEAT_ID_TAG << 120) | region,
                    ledger=FEDERATION_LEDGER, code=SETTLE_CODE,
                )]).tobytes(),
            )

        def outbound_total() -> int:
            return sum(a.core.stats["outbound_seen"] for a in agents)

        def drain(target_outbound: int, phase: str) -> None:
            t0 = time.monotonic()
            while True:
                if time.monotonic() - t0 > settle_deadline_s:
                    raise TimeoutError(
                        f"settlement stalled ({phase}): " + str([
                            (a.region, a.core.pending_count(),
                             a.tail.held_op) for a in agents
                        ])
                    )
                progressed = False
                for a in agents:
                    progressed |= a.step()
                if (outbound_total() >= target_outbound
                        and all(a.idle() for a in agents)):
                    return
                if not progressed:
                    # the tail may be holding the LAST committed op's
                    # group (released only by a higher op): push one
                    for a in agents:
                        if not a.idle() or a.tail.held_op:
                            heartbeat(a.region)
                    time.sleep(0.05)

        t_drive = time.monotonic()
        half = payments // 2
        for r in range(regions):
            issue(r, half)
        drain(half * regions, "pre-kill settle")
        log(f"phase 1 settled: {outbound_total()} outbound legs")

        # second wave lands, then the region-level fault mid-settlement
        for r in range(regions):
            issue(r, payments - half)
        for a in agents:  # partial progress: staged-but-unresolved legs
            a.step()

        victim = rng.randrange(regions) if kill_cluster else None
        if victim is not None:
            for s in servers[victim]:
                if s.alive:
                    s.sigcont()
                    s.kill()
                    report["kills"] += 1
            fleets[victim].mark_fault(time.monotonic())
            log(f"chaos: SIGKILL region {victim} (all {replica_count} "
                f"replicas) mid-settlement")
            time.sleep(restart_after_s)
            for s in servers[victim]:
                s.spawn(wait=False)
                report["restarts"] += 1
            for s in servers[victim]:
                if not s.ready.wait(300.0):
                    raise TimeoutError(
                        f"region {victim} replica {s.index} never "
                        "relistened"
                    )
            log(f"chaos: region {victim} restarted from disk")

        drain(payments * regions, "post-kill settle")
        drive_wall = time.monotonic() - t_drive
        log(f"all {payments * regions} origin pendings settled in "
            f"{drive_wall:.1f}s")

        # -- conservation, over the wire -------------------------------
        def account_row(region: int, account_id: int):
            body = fleets[region].execute(
                fleets[region].sessions[0], Operation.lookup_accounts,
                encode_ids([account_id]),
            )
            arr = decode_accounts(body)
            assert len(arr) == 1, f"missing account {account_id:#x}"
            return arr[0]

        pairs = {}
        for a in range(regions):
            for b in range(regions):
                if a == b:
                    continue
                esc = account_row(a, escrow_account_id(a, b))
                mir = account_row(b, mirror_account_id(b, a))
                posted = int(esc["credits_posted_lo"])
                assert posted == int(mir["debits_posted_lo"]), (
                    f"conservation broken {a}->{b}: escrow {posted} != "
                    f"mirror {int(mir['debits_posted_lo'])}"
                )
                assert int(esc["credits_pending_lo"]) == 0, (
                    f"unresolved escrow residue {a}->{b}"
                )
                assert posted == expected_posted.get((a, b), 0), (
                    f"settled amount drift {a}->{b}: {posted} != "
                    f"{expected_posted.get((a, b), 0)} issued"
                )
                pairs[f"{a}->{b}"] = posted
        log(f"cross-region conservation verified: {pairs}")

        # catch-up barrier before the SIGTERM drain (as run_chaos): the
        # final stream flush can only carry what each replica committed
        for r in range(regions):
            target = fleets[r].max_op
            for s in servers[r]:
                t_w = time.monotonic()
                while True:
                    if time.monotonic() - t_w > 300.0:
                        raise TimeoutError(
                            f"region {r} replica {s.index} never caught "
                            f"up to op {target}"
                        )
                    try:
                        live = inspect_live(
                            "127.0.0.1", region_ports[r][s.index],
                            timeout=2.0,
                        )
                        if live["commit_min"] >= target:
                            break
                    except (OSError, RuntimeError, ValueError):
                        pass
                    time.sleep(0.25)

        # graceful shutdown: replica 0's [stats] carries the published
        # commitment head + the federation identity stamp
        heads = {}
        for r in range(regions):
            for s in servers[r]:
                stats = s.terminate()
                if s.index == 0:
                    fed = stats.get("federation") or {}
                    assert fed.get("region") == r, (r, fed)
                    heads[r] = stats.get("commitments") or {}

        # -- the counterparty audit ------------------------------------
        stream_verify = {}
        for r in range(regions):
            rep = verify_commitment_stream(cdc_paths[r])
            assert rep["ok"], f"region {r} stream verify: {rep}"
            assert rep["checked"] > 0, f"region {r}: no checkpoints"
            assert rep["head_op"] == heads[r].get("head_op"), (
                f"region {r}: verifier head_op {rep['head_op']} != "
                f"published {heads[r].get('head_op')}"
            )
            assert rep["head"] == heads[r].get("head"), (
                f"region {r}: verifier head != published head"
            )
            stream_verify[str(r)] = {
                "checked": rep["checked"],
                "head_op": rep["head_op"],
                "ops_replayed": rep["ops_replayed"],
                "torn_lines": rep.get("torn_lines", 0),
                "redelivered_records": rep.get("redelivered_records", 0),
            }
        log("commitment streams verified against published heads")

        totals = [a.core.stats for a in agents]
        report.update({
            "issued": sum(issued_seq),
            "issued_amount": issued_amount,
            "settled": sum(t["legs_posted"] for t in totals),
            "voided": sum(t["legs_voided"] for t in totals),
            "void_targets": void_targets,
            "redeliveries": sum(t["redeliveries"] for t in totals),
            "settlement_lag_max_ops": max(
                a.max_lag_ops for a in agents
            ),
            "torn_lines": sum(a.tail.torn_lines for a in agents),
            "discarded_groups": sum(
                a.tail.discarded_groups for a in agents
            ),
            "region_killed": victim,
            "recovery_ms": (
                round(fleets[victim].recoveries_ms[0], 1)
                if victim is not None and fleets[victim].recoveries_ms
                else None
            ),
            "conservation": {"ok": True, "settled_amount": pairs},
            "commitment_heads": {
                str(r): [heads[r].get("head_op"), heads[r].get("head")]
                for r in range(regions)
            },
            "stream_verify": stream_verify,
            "wall_s": round(time.monotonic() - t_run, 2),
            "drive_wall_s": round(drive_wall, 2),
        })
        return report
    finally:
        for fleet in fleets:
            fleet.close()
        for row in servers:
            for s in row:
                s.sigcont()
                if s.proc is not None:
                    kill_process_group(s.proc)
        if own_tmp:
            tmp.cleanup()
