"""SimFederation: the seed-deterministic multi-region settlement scenario.

N independent `Simulator` clusters (one per region, each with its own
PacketSimulator, seeded fault schedule, workload clients and commitment
chain) are interleaved tick-by-tick through `Simulator.step()`. A
settlement agent per region tails its region's committed CDC stream
(AOF-backed replica 0, so deep resume never gaps) and settles outbound
legs onto the other regions through raw tick-driven `vsr.Client`
runtimes — the exact sans-IO `SettlementCore` the live driver runs.

Scenario (all draws from seeded rngs, byte-identical per seed):

- issuers mint cross-region pendings (a slice targeting a nonexistent
  beneficiary exercises the void path);
- ONE region is killed wholesale (every replica crashed) mid-settlement
  and later recovers via WAL/superblock recovery;
- agents crash/restart on their own schedule, resuming from the durable
  cursor with the settlement watermark held back;
- after heal: every region converges, every staged leg resolves, and the
  harness proves cross-region conservation (escrow outflow == mirror
  inflow per pair, zero pending residue — zero lost, zero duplicated),
  per-region oracle parity + commitment-chain agreement
  (Simulator._check), and an external StreamVerifier replay of region
  0's captured stream against its published commitments.
"""

from __future__ import annotations

import json
import random

import numpy as np

from tigerbeetle_tpu import types
from tigerbeetle_tpu.federation.agent import HoldbackCursor, SettlementCore
from tigerbeetle_tpu.federation.commitment import StreamVerifier
from tigerbeetle_tpu.federation.topology import (
    FEDERATION_LEDGER,
    SETTLE_CODE,
    FederationTopology,
    escrow_account_id,
    home_account_id,
    mirror_account_id,
    origin_id,
)
from tigerbeetle_tpu.types import (
    CREATE_TRANSFERS_RESULT_DTYPE,
    Account,
    Operation,
    Transfer,
    TransferFlags,
)
from tigerbeetle_tpu.vsr.client import Client, RequestTimeout, SessionEvicted

# Federation client ids live far above the workload clients' id base so
# the two populations never collide in a region's client table.
FED_CLIENT_BASE = 1 << 68
HOME_ACCOUNTS = 4  # pinned user accounts per region


def _dense_codes(reply_body: bytes, n: int) -> list:
    codes = [0] * n
    if reply_body:
        sparse = np.frombuffer(reply_body, dtype=CREATE_TRANSFERS_RESULT_DTYPE)
        for i, code in zip(sparse["index"], sparse["result"]):
            codes[int(i)] = int(code)
    return codes


class _FedClient:
    """A queued, callback-driven wrapper over one tick-runtime Client.
    Requests re-send after eviction (every federation write is
    idempotent by deterministic id, so a re-execution is safe) and
    callbacks fire with the reply's dense result codes."""

    def __init__(self, client: Client):
        self.client = client
        self._queue: list = []  # (operation, body, n_events, callback)
        self._current = None

    def submit(self, operation, body: bytes, n_events: int, callback) -> None:
        self._queue.append((operation, body, n_events, callback))

    @property
    def idle(self) -> bool:
        return self._current is None and not self._queue

    def tick(self) -> None:
        c = self.client
        c.tick()
        try:
            c.poll()
        except (SessionEvicted, RequestTimeout):
            pass  # auto re-register; _current re-sends below
        if c.reply is not None:
            header, body = c.take_reply()
            if header.operation != int(Operation.register):
                cur, self._current = self._current, None
                if cur is not None:
                    cur[3](_dense_codes(body, cur[2]))
        if c.session == 0:
            if c.in_flight is None and not c._want_reregister:
                c.register()
            return
        if c.in_flight is not None:
            return
        if self._current is None and self._queue:
            self._current = self._queue.pop(0)
        if self._current is not None:
            op, body, _n, _cb = self._current
            c.request(op, body)


class _CaptureSink:
    """God's-eye stream capture wrapped around the agent core: dedups by
    op (redelivered ops must re-encode byte-identically — committed
    history never changes) and keeps the full ordered stream for the
    external StreamVerifier replay."""

    def __init__(self, store: dict):
        self.core = None  # swapped on agent restart
        self.store = store  # op -> tuple(lines); shared across agent lives

    def emit_lines(self, lines) -> bool:
        ok = self.core.emit_lines(lines)
        if ok:
            for ln in lines:
                rec = json.loads(ln)
                if rec.get("kind") == "gap":
                    raise AssertionError(f"federation stream gap: {ln}")
            op = json.loads(lines[0])["op"]
            prev = self.store.get(op)
            new = tuple(lines)
            if prev is None or set(prev) < set(new):
                # a redelivery may ADD the commitment line (recorded at
                # dispatch, emitted once the chain entry exists); the
                # change records themselves must be byte-stable
                self.store[op] = new
            else:
                assert set(new) <= set(prev), (op, prev, new)
        return ok

    def flush(self) -> None:
        pass


class SimSettlementAgent:
    """One region's outbound settlement agent with a seeded
    crash/restart schedule. Durable across crashes: the inner cursor and
    the remote ledgers' own dedup. Volatile: the core (staged legs), the
    pump (stream position past the cursor), the holdback stash."""

    def __init__(self, fed: "SimFederation", region: int, seed: int,
                 crash_probability: float):
        from tigerbeetle_tpu.cdc import MemoryCursor

        self.fed = fed
        self.region = region
        self.rng = random.Random(seed * 23 + region * 7 + 3)
        self.crash_probability = crash_probability
        self.cursor = MemoryCursor()  # the durable half
        self.capture = _CaptureSink(fed.streams[region])
        self.crashes = 0
        self.max_lag_ops = 0
        # stats folded across agent lives (a crash drops the core; its
        # counters move here first). At-least-once delivery means these
        # can exceed the unique-event counts — the authoritative checks
        # are conservation + the stream replay, not the counters.
        self.stats_base = {
            "outbound_seen": 0, "legs_posted": 0, "legs_voided": 0,
            "redeliveries": 0, "refusals": 0, "anomalies": 0,
        }
        self._pump = None
        self._core = None
        self._holdback = None
        self._down_until = None

    def _attach(self) -> None:
        from tigerbeetle_tpu.cdc import CdcPump

        sim = self.fed.sims[self.region]
        if self._core is None:  # fresh agent life (start or post-crash)
            self._core = SettlementCore(
                self.fed.topology, self.region,
                window=self.fed.agent_window,
                metrics=sim.replicas[0].metrics,
            )
            self.capture.core = self._core
            self._holdback = HoldbackCursor(self.cursor)
        self._pump = CdcPump(
            sim.replicas[0], self.capture, self._holdback,
            window=32, ack_interval=4,
            aof_path=sim._fanout_aof.name,
            commitments=True,
        )
        self._pump.attach()

    @property
    def core(self):
        return self._core

    def stats_total(self) -> dict:
        out = dict(self.stats_base)
        if self._core is not None:
            for k, v in self._core.stats.items():
                out[k] += v
        return out

    def idle(self) -> bool:
        return (
            self._core is not None
            and self._core.idle()
            and self._down_until is None
        )

    def tick(self, now: int) -> None:
        if self._down_until is not None:
            if now < self._down_until:
                return
            self._down_until = None
        if (
            self._pump is not None
            and self.rng.random() < self.crash_probability
        ):
            # agent SIGKILL: staged legs, stream position and holdback
            # stash all vanish; only the released cursor survives
            self.crashes += 1
            for k, v in self._core.stats.items():
                self.stats_base[k] += v
            self._pump.detach()
            self._pump = self._core = self._holdback = None
            self._down_until = now + self.rng.randint(10, 60)
            return
        sim = self.fed.sims[self.region]
        if self._pump is None:
            self._attach()
        elif self._pump.replica is not sim.replicas[0]:
            # the tailed replica restarted: re-subscribe (redelivered
            # ops dedup in the core / the remote ledger)
            self._pump.detach()
            self._attach()
        if 0 not in sim.down:
            self._pump.pump(budget_ops=4)
            lag = sim.replicas[0].cdc_commit_min - self._core.watermark()
            self.max_lag_ops = max(self.max_lag_ops, lag)
        core = self._core
        if core.error is not None:
            raise AssertionError(f"agent r{self.region}: {core.error}")
        # mirror legs outward
        for dst in sorted(core.dsts_with_work()):
            fc = self.fed.fed_client(self.region, dst)
            if not fc.idle:
                continue  # one staged batch in flight per lane
            legs = core.next_mirror_batch(dst, limit=8)
            if legs:
                body = types.transfers_to_np(
                    core.mirror_transfers(legs)
                ).tobytes()
                fc.submit(
                    Operation.create_transfers, body, len(legs),
                    lambda codes, _legs=legs, _c=core:
                        _c.on_mirror_replies(_legs, codes),
                )
        # resolve legs home
        fc = self.fed.fed_client(self.region, self.region)
        if fc.idle:
            legs = core.next_resolve_batch(limit=8)
            if legs:
                body = types.transfers_to_np(
                    core.resolve_transfers(legs)
                ).tobytes()
                fc.submit(
                    Operation.create_transfers, body, len(legs),
                    lambda codes, _legs=legs, _c=core:
                        _c.on_resolve_replies(_legs, codes),
                )
        self._holdback.release(core.watermark())


class _Issuer:
    """Seeded cross-region payment source on one region: mints origin
    pendings (debit a home payer, credit the pair escrow) through a fed
    client. A small slice targets a nonexistent beneficiary to exercise
    the agent's void path."""

    def __init__(self, fed: "SimFederation", region: int, seed: int,
                 rate: float, void_fraction: float = 0.1):
        self.fed = fed
        self.region = region
        self.rng = random.Random(seed * 29 + region * 11 + 1)
        self.rate = rate
        self.void_fraction = void_fraction
        self.seq = 0
        self.issued_amount = 0

    def tick(self, now: int) -> None:
        if self.rng.random() >= self.rate:
            return
        fc = self.fed.fed_client(self.region, self.region)
        if not fc.idle:
            return
        n_regions = self.fed.topology.n
        batch = []
        for _ in range(self.rng.randint(1, 4)):
            dst = self.rng.choice(
                [r for r in range(n_regions) if r != self.region]
            )
            payer = home_account_id(
                self.region, self.rng.randrange(HOME_ACCOUNTS), n_regions
            )
            if self.rng.random() < self.void_fraction:
                # beyond the created range: the mirror leg will bounce
                # with credit_account_not_found and the origin voids
                beneficiary = home_account_id(
                    dst, HOME_ACCOUNTS + self.rng.randrange(4), n_regions
                )
            else:
                beneficiary = home_account_id(
                    dst, self.rng.randrange(HOME_ACCOUNTS), n_regions
                )
            self.seq += 1
            amount = self.rng.randint(1, 100)
            self.issued_amount += amount
            batch.append(Transfer(
                id=origin_id(self.region, self.seq),
                debit_account_id=payer,
                credit_account_id=escrow_account_id(self.region, dst),
                amount=amount,
                ledger=FEDERATION_LEDGER,
                code=SETTLE_CODE,
                flags=int(TransferFlags.pending),
                user_data_128=beneficiary,
            ))
        fc.submit(
            Operation.create_transfers,
            types.transfers_to_np(batch).tobytes(),
            len(batch),
            lambda codes: None,  # idempotent ids; re-send dedups remotely
        )


class SimFederation:
    """The composite harness (see module docstring)."""

    def __init__(
        self,
        seed: int,
        n_regions: int = 2,
        ticks: int = 2600,
        commitment_interval: int = 20,
        replica_count: int = 3,
        agent_crash_probability: float = 0.004,
        agent_window: int = 64,
        issue_rate: float = 0.25,
        region_kill: bool = True,
        kill_outage_ticks: int = 260,
        verify_stream: bool = True,
        sim_knobs: dict | None = None,
    ):
        from tigerbeetle_tpu.testing.simulator import Simulator

        self.seed = seed
        self.ticks = ticks
        self.topology = FederationTopology.of(n_regions)
        self.agent_window = agent_window
        self.verify_stream = verify_stream
        self.rng = random.Random(seed * 17 + 9)
        # op -> tuple(lines), per region: the god's-eye captured stream
        self.streams: list = [dict() for _ in range(n_regions)]
        knobs = dict(
            replica_count=replica_count,
            n_clients=1,
            ticks=ticks,
            crash_probability=0.0005,
            wal_fault_probability=0.1,
            torn_write_probability=0.1,
            commitment_interval=commitment_interval,
            tail_aof=True,
        )
        knobs.update(sim_knobs or {})
        self.sims = [
            Simulator(seed=seed * 1000003 + r, **knobs)
            for r in range(n_regions)
        ]
        self.agents = [
            SimSettlementAgent(self, r, seed, agent_crash_probability)
            for r in range(n_regions)
        ]
        self.issuers = [
            _Issuer(self, r, seed, rate=issue_rate)
            for r in range(n_regions)
        ]
        # fed clients keyed (owner region, target region); created lazily
        self._fed_clients: dict = {}
        # scripted region-wide kill, drawn mid-run
        self.kill_region = (
            self.rng.randrange(n_regions) if region_kill else None
        )
        self.kill_tick = (
            self.rng.randint(ticks // 3, ticks // 2) if region_kill else None
        )
        self.kill_outage_ticks = kill_outage_ticks
        self._bootstrapped = [False] * n_regions
        self._draining = False
        self._bootstrap()

    # -- plumbing ------------------------------------------------------

    def fed_client(self, owner: int, target: int) -> _FedClient:
        key = (owner, target)
        fc = self._fed_clients.get(key)
        if fc is None:
            sim = self.sims[target]
            fc = _FedClient(Client(
                FED_CLIENT_BASE + owner * 64 + target,
                sim.net, sim.replica_count,
                request_timeout_ticks=30,
                max_backoff_exponent=2,
                ping_ticks=40,
                auto_reregister=True,
            ))
            self._fed_clients[key] = fc
        return fc

    def _bootstrap(self) -> None:
        """Queue every region's infrastructure accounts (escrows, mirrors,
        pinned home users) before any traffic: idempotent creates through
        the region's own fed client."""
        n = self.topology.n
        for region in range(n):
            ids = self.topology.infra_account_ids(region) + [
                home_account_id(region, k, n) for k in range(HOME_ACCOUNTS)
            ]
            accounts = [
                Account(id=i, ledger=FEDERATION_LEDGER, code=SETTLE_CODE)
                for i in ids
            ]

            def _done(codes, _r=region):
                self._bootstrapped[_r] = True

            self.fed_client(region, region).submit(
                Operation.create_accounts,
                types.accounts_to_np(accounts).tobytes(),
                len(accounts),
                _done,
            )

    def _tick_federation(self, now: int) -> None:
        if all(self._bootstrapped) and not self._draining:
            # mirror legs must never outrun a peer's infra accounts
            for issuer in self.issuers:
                issuer.tick(now)
        for agent in self.agents:
            agent.tick(now)
        for key in sorted(self._fed_clients):
            self._fed_clients[key].tick()

    def _kill_region(self, victim: int) -> None:
        sim = self.sims[victim]
        now = sim.net.tick_now
        for i in range(sim.replica_count):
            if i not in sim.down:
                sim._crash(i, now)
            # stretch the outage past the seeded restart draw: the whole
            # region is dark, not flapping
            sim.down[i] = now + self.kill_outage_ticks
        self.killed_at = now

    # -- the run -------------------------------------------------------

    def run(self) -> dict:
        try:
            return self._run()
        finally:
            import os

            for sim in self.sims:
                if sim._fanout_aof is not None:
                    try:
                        os.unlink(sim._fanout_aof.name)
                    except OSError:
                        pass

    def _run(self) -> dict:
        for t in range(self.ticks):
            if self.kill_tick is not None and t == self.kill_tick:
                self._kill_region(self.kill_region)
            for sim in self.sims:
                sim.step()
            self._tick_federation(t)

        self._heal_and_settle()
        for sim in self.sims:
            sim._check()
        conservation = self._check_conservation()
        verify = self._verify_streams() if self.verify_stream else None
        totals = [a.stats_total() for a in self.agents]
        settled = sum(t["legs_posted"] for t in totals)
        voided = sum(t["legs_voided"] for t in totals)
        issued = sum(i.seq for i in self.issuers)
        return {
            "seed": self.seed,
            "regions": self.topology.n,
            "committed_ops": [
                max(max(h) if h else 0 for h in sim.histories)
                for sim in self.sims
            ],
            "issued": issued,
            "settled": settled,
            "voided": voided,
            "agent_crashes": sum(a.crashes for a in self.agents),
            "agent_redeliveries": sum(t["redeliveries"] for t in totals),
            "settlement_lag_max_ops": max(
                a.max_lag_ops for a in self.agents
            ),
            "region_killed": self.kill_region,
            "conservation": conservation,
            "commitment_heads": [
                [sim.replicas[0].commitment_log.head_op,
                 sim.replicas[0].commitment_log.head]
                for sim in self.sims
            ],
            "stream_verify": verify,
        }

    def _heal_and_settle(self) -> None:
        """Heal every region, then keep ticking until every origin
        pending has settled and every region has converged."""
        self._draining = True  # no new mints; settle what's in flight
        for sim in self.sims:
            sim.net.clear_partitions()
            sim.net.options.partition_probability = 0.0
            sim.net.options.packet_loss_probability = 0.0
            sim.crash_probability = 0.0
            for c in sim.clients:
                c.drain_mode = True
            for i in list(sim.down):
                del sim.down[i]
                sim.net.crashed.discard(i)
                sim.replicas[i] = sim._make_replica(i)
        for agent in self.agents:
            agent.crash_probability = 0.0
        budget = 4000
        for t in range(budget):
            for sim in self.sims:
                sim.step()
            self._tick_federation(self.ticks + t)
            if self._quiesced():
                return
        raise AssertionError(
            "federation failed to settle within the heal budget: "
            + str([
                (a.region, a.core.pending_count() if a.core else None)
                for a in self.agents
            ])
        )

    def _quiesced(self) -> bool:
        for sim in self.sims:
            mins = {r.commit_min for r in sim.replicas}
            stats = {r.status for r in sim.replicas}
            if len(mins) != 1 or stats != {"normal"}:
                return False
            if any(c.client.in_flight is not None for c in sim.clients):
                return False
        if not all(
            fc.idle and fc.client.in_flight is None
            for fc in self._fed_clients.values()
        ):
            return False
        for agent in self.agents:
            if not agent.idle():
                return False
            sim = self.sims[agent.region]
            if agent._pump.next_op <= sim.replicas[0].cdc_commit_min:
                return False  # stream not fully drained yet
        return True

    # -- federation checks ---------------------------------------------

    def _account(self, region: int, account_id: int):
        got = self.sims[region].replicas[0].ledger.lookup_accounts(
            [account_id]
        )
        return got[0] if got else None

    def _check_conservation(self) -> dict:
        """Cross-region conservation, on the CONVERGED ledgers: for each
        ordered pair (a, b), escrow(a->b) outflow on a equals mirror
        inflow on b (posted legs), and no pending residue anywhere —
        zero lost, zero duplicated."""
        n = self.topology.n
        pairs = {}
        for a in range(n):
            for b in range(n):
                if a == b:
                    continue
                esc = self._account(a, escrow_account_id(a, b))
                mir = self._account(b, mirror_account_id(b, a))
                if esc is None and mir is None:
                    continue
                assert esc is not None and mir is not None, (a, b)
                # posted escrow credits == origin pendings POSTED; the
                # mirror's posted debits are the matching legs on b
                assert esc.credits_posted == mir.debits_posted, (
                    f"conservation broken {a}->{b}: escrow "
                    f"{esc.credits_posted} != mirror {mir.debits_posted}"
                )
                assert esc.credits_pending == 0, (
                    f"unresolved escrow residue {a}->{b}: "
                    f"{esc.credits_pending}"
                )
                pairs[f"{a}->{b}"] = esc.credits_posted
        return {"ok": True, "settled_amount": pairs}

    def _verify_streams(self) -> dict:
        """The external-consumer acceptance check: replay every region's
        captured CDC stream through a fresh oracle and re-derive the
        commitment chain — the recomputed head must equal the replica's
        published chain at the same checkpoint."""
        out = {}
        for region, stream in enumerate(self.streams):
            v = StreamVerifier()
            for op in sorted(stream):
                v.feed_lines(stream[op])
            rep = v.report()
            assert rep["ok"], f"region {region} stream verify: {rep}"
            clog = self.sims[region].replicas[0].commitment_log
            assert rep["checked"] > 0, f"region {region}: no checkpoints"
            assert rep["head_op"] == clog.head_op and rep["head"] == clog.head, (
                f"region {region}: verifier head "
                f"({rep['head_op']}, {rep['head']:#x}) != replica "
                f"({clog.head_op}, {clog.head:#x})"
            )
            out[region] = {"checked": rep["checked"], "head_op": rep["head_op"]}
        return out


def run_federation_sim(seed: int, **kw) -> dict:
    """One-call entry point (vopr slice, tests, bench)."""
    return SimFederation(seed, **kw).run()
