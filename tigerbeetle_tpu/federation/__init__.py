"""Cross-ledger federation: N regional clusters, settled through CDC.

A federation is N independent VSR clusters ("regions"), each owning the
accounts that hash to it. Cross-region transfers never run global
consensus: the origin region commits a two-phase PENDING leg against a
per-pair escrow account, a settlement agent (a CDC consumer) mirrors the
leg on the destination region, then posts or voids the origin pending —
at-least-once, idempotent, resumable from a durable cursor. Checkpoint
state commitments (a chained digest over the ledger's groove-row
fingerprints) let a counterparty verify a region's stream against its
published state without trusting it.

Module map:

- `commitment`: CommitmentLog (the per-replica checkpoint chain) and
  StreamVerifier (the external consumer's replay-and-check).
- `topology`: declarative region topology — owner-hash routing,
  escrow/mirror account derivation, deterministic settlement ids.
- `agent`: SettlementCore, the sans-IO settlement state machine that
  rides a CdcPump sink on one side and two client runtimes on the other.
- `sim`: SimFederation — the seed-deterministic multi-region simulator
  scenario (region kill mid-settlement, conservation proven on recovery).
- `live`: the wall-clock two-region driver (subprocess clusters), used
  by `scripts/federate.py` and the chaos harness's `--kill-cluster`.
"""

from tigerbeetle_tpu.federation.commitment import (
    CommitmentLog,
    CommitmentMismatch,
    StreamVerifier,
    fold_commitment,
)
from tigerbeetle_tpu.federation.topology import (
    FEDERATION_LEDGER,
    SETTLE_CODE,
    FederationTopology,
    RegionSpec,
)

__all__ = [
    "CommitmentLog",
    "CommitmentMismatch",
    "StreamVerifier",
    "fold_commitment",
    "FederationTopology",
    "RegionSpec",
    "FEDERATION_LEDGER",
    "SETTLE_CODE",
]
