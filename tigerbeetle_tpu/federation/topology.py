"""Declarative federation topology: regions, routing, account derivation.

Ownership is by id hash: an untagged account id belongs to
`region_of(id)`. The federation's own infrastructure accounts are tagged
in the top byte of the 128-bit id space (ids clients never mint — the
workload generator's odd golden-ratio ids and real client ids land in
the untagged space):

    0xAC  home (user) account pinned to a region (salt rejection-sampled
          until the owner hash agrees with the pin)
    0xE5  escrow account for pair (src -> dst), lives on src
    0xA1  mirror account for pair (dst <- src), lives on dst
    0xC0  origin pending-transfer ids minted by an issuer on src
    0x5E  settlement-leg transfer ids minted by the agent (deterministic
          per (src, op, ix, leg) — the REMOTE ledger is the dedup
          authority: a redelivered leg hits `exists`, which counts as
          success)

Cross-region money flow for A -> B of `amount`:

    on A: pending  debit=payer,        credit=escrow(A->B)   [origin]
    on B: posted   debit=mirror(B<-A), credit=beneficiary    [leg 0]
    on A: post_pending of the origin (or void on terminal failure)
                                                             [leg 1]

Conservation invariant (checked by SimFederation and the chaos
harness): escrow(A->B).credits_posted on A == mirror(B<-A).debits_posted
on B, and escrow credits_pending drains to zero once settlement
quiesces — zero lost, zero duplicated.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from tigerbeetle_tpu.federation.commitment import _mix64

# All federation traffic lives on its own ledger: settlement legs can
# never collide with workload ledgers, and per-ledger conservation
# (oracle.verify_conservation) applies to the federation flow alone.
FEDERATION_LEDGER = 0xFED
SETTLE_CODE = 0x5E7

_M64 = (1 << 64) - 1
U128_MAX = (1 << 128) - 1

TAG_SHIFT = 120
TAG_HOME = 0xAC
TAG_ESCROW = 0xE5
TAG_MIRROR = 0xA1
TAG_ORIGIN = 0xC0
TAG_SETTLE = 0x5E

MAX_REGIONS = 16  # settlement ids carry the region in a 4-bit field


def tag_of(account_id: int) -> int:
    return (account_id >> TAG_SHIFT) & 0xFF


def region_of(account_id: int, n_regions: int) -> int:
    """Owner region of an UNTAGGED id (64-bit-folded hash mod N)."""
    return _mix64((account_id & _M64) ^ (account_id >> 64)) % n_regions


def escrow_account_id(src: int, dst: int) -> int:
    """The (src -> dst) escrow, held on src: origin pendings credit it;
    posting the origin moves the money into it for good."""
    return (TAG_ESCROW << TAG_SHIFT) | (src << 112) | (dst << 104)


def mirror_account_id(dst: int, src: int) -> int:
    """The (dst <- src) mirror, held on dst: settlement legs debit it —
    it is src's liability column on dst's books."""
    return (TAG_MIRROR << TAG_SHIFT) | (dst << 112) | (src << 104)


def escrow_pair(account_id: int) -> Tuple[int, int]:
    return (account_id >> 112) & 0xFF, (account_id >> 104) & 0xFF


def settlement_id(src: int, op: int, ix: int, leg: int) -> int:
    """Deterministic settlement-leg transfer id for origin event
    (src region, committed op, event index). leg 0 = the mirror transfer
    on dst; leg 1 = the post/void of the origin pending on src. Pure
    function of the committed origin stream -> idempotent across agent
    crash/redelivery."""
    return (
        (TAG_SETTLE << TAG_SHIFT)
        | ((src & 0xF) << 116)
        | ((leg & 0xF) << 112)
        | ((op & ((1 << 80) - 1)) << 24)
        | (ix & 0xFFFFFF)
    )


def origin_id(src: int, seq: int) -> int:
    """Origin pending-transfer id minted by an issuer on src."""
    return (TAG_ORIGIN << TAG_SHIFT) | ((src & 0xFF) << 112) | (seq & ((1 << 112) - 1))


def home_account_id(region: int, k: int, n_regions: int) -> int:
    """The k-th user account pinned to `region`: tagged base + the
    smallest salt whose owner hash lands on the region (expected
    n_regions tries; deterministic — every replica and the sim twin
    derive the same id)."""
    base = (TAG_HOME << TAG_SHIFT) | ((region & 0xFF) << 112) | ((k & _M64) << 32)
    for salt in range(1 << 20):
        cand = base | salt
        if region_of(cand, n_regions) == region:
            return cand
    raise AssertionError("unreachable: owner hash never landed")


@dataclasses.dataclass
class RegionSpec:
    """One region of the federation. `addresses` is the live mode's
    replica address list (host:port per replica); sim regions leave it
    empty and carry only the name/index."""

    index: int
    name: str = ""
    addresses: tuple = ()
    data_dir: Optional[str] = None

    def __post_init__(self):
        if not self.name:
            self.name = f"r{self.index}"


class FederationTopology:
    """The declarative N-region map every federation component shares:
    the settlement agent routes by it, the sim builds clusters from it,
    the live driver spawns processes from it."""

    def __init__(self, regions: List[RegionSpec]):
        assert 2 <= len(regions) <= MAX_REGIONS, len(regions)
        assert [r.index for r in regions] == list(range(len(regions)))
        self.regions = regions

    @property
    def n(self) -> int:
        return len(self.regions)

    @classmethod
    def of(cls, n_regions: int) -> "FederationTopology":
        return cls([RegionSpec(index=i) for i in range(n_regions)])

    def region_of(self, account_id: int) -> int:
        """Owner region of any account id, tagged or not."""
        tag = tag_of(account_id)
        if tag == TAG_ESCROW:
            return escrow_pair(account_id)[0]
        if tag == TAG_MIRROR:
            return escrow_pair(account_id)[0]
        if tag == TAG_HOME:
            return (account_id >> 112) & 0xFF
        return region_of(account_id, self.n)

    def escrow(self, src: int, dst: int) -> int:
        assert src != dst
        return escrow_account_id(src, dst)

    def mirror(self, dst: int, src: int) -> int:
        assert src != dst
        return mirror_account_id(dst, src)

    def infra_account_ids(self, region: int) -> List[int]:
        """Every escrow/mirror account `region` must hold (one per remote
        peer, each direction) — created once at federation bootstrap."""
        out = []
        for other in range(self.n):
            if other == region:
                continue
            out.append(self.escrow(region, other))
            out.append(self.mirror(region, other))
        return out

    # -- stream classification (the agent's routing predicate) ---------

    def classify_outbound(self, rec: dict, region: int) -> Optional[dict]:
        """Is this committed change record an origin pending leaving
        `region`? Returns {dst, beneficiary, amount} or None. Matches
        only SUCCESSFUL two-phase pendings on the federation ledger that
        credit one of this region's outbound escrows; settlement legs
        the agent itself writes never match (mirror legs are plain
        posted transfers, resolve legs carry post/void flags)."""
        from tigerbeetle_tpu.types import TransferFlags

        if rec.get("kind") != "transfer" or rec.get("result") != 0:
            return None
        if rec.get("ledger") != FEDERATION_LEDGER:
            return None
        if rec.get("code") != SETTLE_CODE:
            return None
        flags = int(rec.get("flags", 0))
        if not flags & int(TransferFlags.pending):
            return None
        if flags & (
            int(TransferFlags.post_pending_transfer)
            | int(TransferFlags.void_pending_transfer)
        ):
            return None
        credit = int(rec["credit_account_id"])
        if tag_of(credit) != TAG_ESCROW:
            return None
        src, dst = escrow_pair(credit)
        if src != region or dst == region:
            return None
        return {
            "dst": dst,
            "beneficiary": int(rec.get("user_data_128", 0)),
            "amount": int(rec["amount"]),
        }
