"""Checkpoint state commitments: a chained digest over ledger fingerprints.

The per-op hash-log ring (PR 8) authenticates the *request stream*; it
says nothing about the state a replica claims to have reached. The
commitment chain closes that gap: at every commitment boundary (op
multiple of the configured interval) the replica folds the backend's
state fingerprint — the same five-field surface the dual applier already
compares at finalize — into a running u64 chain:

    C_k = fold(C_{k-1}, op_k, fingerprint(op_k))

The fingerprint is a pure function of committed history (content-only
per-row hash, commutative sum — slot-order independent), so every
replica, the native backend, the dual device twin, and the numpy oracle
all compute bit-identical chains from the same stream. A counterparty
that replays a region's CDC stream through its own oracle recomputes the
chain and rejects a tampered stream or state *naming the exact
checkpoint op* where histories diverge.

All arithmetic here is plain python ints masked to 64 bits — no device,
no numpy — so the fold is trivially portable to any consumer.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

# Shared with models/ledger.py's _fp_rows / _fp_mix (murmur3/xxhash
# finalizer constants). The native kernel (tb_ledger_fingerprint) and
# the device kernel implement the identical row fold; this module only
# *chains* their outputs, but reuses the same mixing constants so there
# is one constant set to keep in sync across implementations.
_FP_MUL = 0xC2B2AE3D27D4EB4F
_FP_ADD = 0x165667B19E3779F9
_FP_MIX1 = 0xFF51AFD7ED558CCD
_FP_MIX2 = 0xC4CEB9FE1A85EC53

_M64 = (1 << 64) - 1

# The exact fingerprint surface folded into the chain, in fold order.
# NativeLedger.fingerprint() returns extra keys (e.g. "posted"); the
# chain uses only these five so every backend agrees on the input.
FP_FIELDS = (
    "accounts_fp",
    "transfers_fp",
    "accounts",
    "transfers",
    "commit_timestamp",
)


def _mix64(x: int) -> int:
    x &= _M64
    x = ((x ^ (x >> 33)) * _FP_MIX1) & _M64
    x = ((x ^ (x >> 33)) * _FP_MIX2) & _M64
    return x ^ (x >> 33)


def fold_commitment(prev: int, op: int, fp: Dict[str, int]) -> int:
    """Fold one checkpoint fingerprint into the chain.

    `fp` may carry extra keys; only FP_FIELDS participate. Pure python
    ints — callable from any consumer without the repo's device stack.
    """
    h = prev & _M64
    for x in (op, *(fp[k] for k in FP_FIELDS)):
        h = _mix64(((h ^ (int(x) & _M64)) * _FP_MUL + _FP_ADD) & _M64)
    return h


def fp_tuple(fp: Dict[str, int]) -> Tuple[int, ...]:
    return tuple(int(fp[k]) & _M64 for k in FP_FIELDS)


class CommitmentMismatch(Exception):
    """A commitment check failed; `.op` names the divergent checkpoint."""

    def __init__(self, op: int, why: str):
        super().__init__(f"commitment mismatch at checkpoint op={op}: {why}")
        self.op = op
        self.why = why


class CommitmentLog:
    """The per-replica commitment chain with a bounded entry ring.

    Commitments are recorded at commit-dispatch time (state exact after
    the boundary op applies) and are idempotent: a WAL-tail replay or a
    redelivered dispatch re-records the same op, and the stored
    fingerprint must match bit-exactly — a replica whose state groove
    was tampered between runs raises CommitmentMismatch naming the
    checkpoint. The ring keeps the most recent `ring` entries; the head
    (op, commitment) pair is always retained, so chains survive
    arbitrarily long histories and state-sync gaps (the snapshot source
    records every boundary up to its checkpoint, so a restored head is
    always the last boundary before commit_min).
    """

    def __init__(self, interval: int, ring: int = 256):
        if interval <= 0:
            raise ValueError("commitment interval must be positive")
        self.interval = int(interval)
        self.ring = int(ring)
        self.head_op = 0
        self.head = 0  # chain value at head_op (0 == genesis)
        # op -> (commitment, prev, fp_tuple), ascending op order
        self._entries: Dict[int, Tuple[int, int, Tuple[int, ...]]] = {}

    # -- recording -----------------------------------------------------

    def is_boundary(self, op: int) -> bool:
        return op > 0 and op % self.interval == 0

    def record(self, op: int, fp: Dict[str, int]) -> Optional[int]:
        """Record (or idempotently re-verify) the checkpoint at `op`."""
        t = fp_tuple(fp)
        if op <= self.head_op:
            ent = self._entries.get(op)
            if ent is None:
                return None  # older than the ring: blind, accept
            if ent[2] != t:
                raise CommitmentMismatch(
                    op, f"re-recorded fingerprint {t} != stored {ent[2]}"
                )
            return ent[0]
        if op != self.head_op + self.interval:
            raise CommitmentMismatch(
                op,
                f"non-contiguous boundary (head={self.head_op}, "
                f"interval={self.interval})",
            )
        c = fold_commitment(self.head, op, fp)
        self._entries[op] = (c, self.head, t)
        self.head_op = op
        self.head = c
        if len(self._entries) > self.ring:
            for old in sorted(self._entries):
                if len(self._entries) <= self.ring:
                    break
                del self._entries[old]
        return c

    # -- queries -------------------------------------------------------

    def get(self, op: int) -> Optional[Tuple[int, int]]:
        """(commitment, prev) at `op`, or None if outside the ring."""
        ent = self._entries.get(op)
        return None if ent is None else (ent[0], ent[1])

    def fingerprint_at(self, op: int) -> Optional[Dict[str, int]]:
        ent = self._entries.get(op)
        if ent is None:
            return None
        return dict(zip(FP_FIELDS, ent[2]))

    def ops(self) -> List[int]:
        return sorted(self._entries)

    def first_divergence(self, other: "CommitmentLog") -> Optional[int]:
        """First overlapping checkpoint op where two chains disagree."""
        shared = sorted(set(self._entries) & set(other._entries))
        for op in shared:
            if self._entries[op][0] != other._entries[op][0]:
                return op
        if (
            not shared
            and self.head_op
            and self.head_op == other.head_op
            and self.head != other.head
        ):
            return self.head_op
        return None

    def stats_snapshot(self, limit: int = 16) -> Dict[str, object]:
        """Trimmed view for the [stats] snapshot / `inspect commitments
        --live`: the chain head plus the most recent `limit` checkpoints
        as [op, commitment, prev] rows."""
        ops = sorted(self._entries)[-limit:]
        return {
            "interval": self.interval,
            "head_op": self.head_op,
            "head": self.head,
            "recent": [
                [op, self._entries[op][0], self._entries[op][1]] for op in ops
            ],
        }

    # -- persistence (checkpoint extra_meta; JSON-safe ints) -----------

    def snapshot(self) -> Dict[str, object]:
        return {
            "interval": self.interval,
            "head_op": self.head_op,
            "head": self.head,
            "entries": [
                [op, c, prev, list(t)]
                for op, (c, prev, t) in sorted(self._entries.items())
            ],
        }

    def restore(self, data: Optional[Dict[str, object]]) -> None:
        if not data:
            return
        self.interval = int(data["interval"])
        self.head_op = int(data["head_op"])
        self.head = int(data["head"])
        self._entries = {
            int(op): (int(c), int(prev), tuple(int(x) for x in t))
            for op, c, prev, t in data["entries"]
        }


class StreamVerifier:
    """The external consumer: replay a CDC stream, re-derive the chain.

    Feeds every record line of a region's CDC stream (from op 1 — an
    AOF-backed tail never gaps) through a fresh numpy oracle, re-executes
    each committed batch, cross-checks recorded per-event results, and at
    every `commitment` record recomputes the chain from the oracle's own
    fingerprint. A stream whose history was tampered — an edited amount,
    a dropped event, a forged commitment — fails at the exact checkpoint
    where the recomputed chain first disagrees.

    Sans-IO: call `feed(record_dict)` per parsed JSON record (or
    `feed_lines` for raw JSONL) and read `.report()`.
    """

    def __init__(self, strict_results: bool = True):
        # Local import: federation must stay importable without pulling
        # the device stack until a verifier is actually constructed.
        from tigerbeetle_tpu.models.oracle import OracleStateMachine

        self.oracle = OracleStateMachine()
        self.strict_results = bool(strict_results)
        self.head = 0
        self.head_op = 0
        self.checked = 0
        self.ops_replayed = 0
        self.first_divergent: Optional[int] = None
        self.error: Optional[str] = None
        self.gapped = False
        self._batch: List[dict] = []

    # -- feeding -------------------------------------------------------

    def feed_lines(self, lines: Iterable[str]) -> None:
        import json

        for line in lines:
            line = line.strip()
            if line:
                self.feed(json.loads(line))

    def feed(self, rec: dict) -> None:
        if self.error is not None:
            return
        kind = rec.get("kind")
        if kind == "gap":
            self._flush_batch()
            self.gapped = True
            self.error = (
                f"stream gap {rec.get('from')}..{rec.get('to')}: "
                "history unverifiable from here"
            )
            return
        if kind == "commitment":
            self._flush_batch()
            self._check_commitment(rec)
            return
        if kind not in ("account", "transfer"):
            return
        if self._batch and self._batch[-1]["op"] != rec["op"]:
            self._flush_batch()
        self._batch.append(rec)

    # -- replay --------------------------------------------------------

    def _flush_batch(self) -> None:
        if not self._batch:
            return
        recs, self._batch = self._batch, []
        from tigerbeetle_tpu.types import Account, Operation, Transfer

        kind = recs[0]["kind"]
        op = recs[0]["op"]
        timestamp = recs[-1]["ts"]
        if kind == "account":
            operation = Operation.create_accounts
            events = [
                Account(
                    id=r["id"],
                    ledger=r["ledger"],
                    code=r["code"],
                    flags=r["flags"],
                    user_data_128=r.get("user_data_128", 0),
                    user_data_64=r.get("user_data_64", 0),
                    user_data_32=r.get("user_data_32", 0),
                    # nonzero only on INVALID creates — carried so the
                    # replay reproduces the validation result codes
                    debits_pending=r.get("debits_pending", 0),
                    debits_posted=r.get("debits_posted", 0),
                    credits_pending=r.get("credits_pending", 0),
                    credits_posted=r.get("credits_posted", 0),
                    reserved=r.get("reserved", 0),
                )
                for r in recs
            ]
        else:
            operation = Operation.create_transfers
            events = [
                Transfer(
                    id=r["id"],
                    debit_account_id=r["debit_account_id"],
                    credit_account_id=r["credit_account_id"],
                    amount=r["amount"],
                    pending_id=r.get("pending_id", 0),
                    timeout=r.get("timeout", 0),
                    ledger=r["ledger"],
                    code=r["code"],
                    flags=r["flags"],
                    user_data_128=r.get("user_data_128", 0),
                    user_data_64=r.get("user_data_64", 0),
                    user_data_32=r.get("user_data_32", 0),
                )
                for r in recs
            ]
        results = self.oracle.execute_dense(operation, timestamp, events)
        self.ops_replayed += 1
        if not self.strict_results:
            return
        for r, got in zip(recs, results):
            want = r.get("result")
            if want is not None and int(got) != int(want):
                self.error = (
                    f"op={op} ix={r['ix']}: replay result {int(got)} != "
                    f"recorded {int(want)}"
                )
                return

    def _check_commitment(self, rec: dict) -> None:
        op = int(rec["op"])
        claimed = int(rec["commitment"])
        claimed_prev = int(rec.get("prev", self.head))
        if claimed_prev != self.head:
            self.first_divergent = op
            self.error = (
                f"checkpoint op={op}: chain prev {claimed_prev:#x} != "
                f"replayed head {self.head:#x}"
            )
            return
        fp = self.oracle.fingerprint()
        c = fold_commitment(self.head, op, fp)
        if c != claimed:
            self.first_divergent = op
            self.error = (
                f"checkpoint op={op}: recomputed commitment {c:#x} != "
                f"claimed {claimed:#x} (state/stream tampered at or "
                f"before this checkpoint)"
            )
            return
        self.head = c
        self.head_op = op
        self.checked += 1

    # -- results -------------------------------------------------------

    def finish(self) -> None:
        self._flush_batch()

    def report(self) -> Dict[str, object]:
        self.finish()
        return {
            "ok": self.error is None,
            "checked": self.checked,
            "head_op": self.head_op,
            "head": self.head,
            "ops_replayed": self.ops_replayed,
            "first_divergent": self.first_divergent,
            "error": self.error,
        }
