"""The settlement agent: a CDC consumer that settles cross-region legs.

Sans-IO core. `SettlementCore` plugs into a CdcPump (or fan-out hub) AS
THE SINK on the origin region's committed stream, recognizes outbound
two-phase pendings (topology.classify_outbound), and stages the two
settlement legs per origin event:

    leg 0 (mirror):  a plain posted transfer on the DESTINATION region
                     (debit the pair mirror, credit the beneficiary)
    leg 1 (resolve): post_pending of the origin on the ORIGIN region —
                     or void_pending when the mirror leg failed
                     terminally (e.g. the beneficiary does not exist)

Drivers (federation/sim.py tick-based, federation/live.py wall-clock)
own the client runtimes and the loop: they pull staged batches, send
them through the PR 10 fault-tolerant clients, and feed replies back.
The core never reads a clock and never talks to a socket, so the sim
scenario replays it byte-identically.

Delivery contract — at-least-once, exactly-once effects:

- Backpressure BEFORE staging: `emit_lines` refuses the whole op when
  the in-flight window is full; the pump retries it later (the tail
  still holds the op). An accepted op is staged atomically.
- Settlement-leg ids are a pure function of (src region, origin op,
  event index, leg) — the REMOTE ledger is the dedup authority. After a
  crash the agent replays from its cursor and re-sends legs; `exists`
  (and the already_posted/already_voided family on resolves) counts as
  success, so redelivery never double-moves money.
- The durable cursor is held back (`HoldbackCursor`) to the settlement
  watermark: it only persists ops whose every staged leg has resolved,
  so a crash between cursor write and leg completion is impossible —
  the replay window always covers unfinished work.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional, Set

from tigerbeetle_tpu.federation.topology import (
    FEDERATION_LEDGER,
    SETTLE_CODE,
    FederationTopology,
    settlement_id,
)
from tigerbeetle_tpu.types import CreateTransferResult as R
from tigerbeetle_tpu.types import Transfer, TransferFlags

# Mirror-leg replies that mean "the money is on the destination":
_MIRROR_OK = (int(R.ok), int(R.exists))
# Resolve-leg replies that mean "the origin pending is closed":
_RESOLVE_DONE = (
    int(R.ok),
    int(R.exists),
    int(R.pending_transfer_already_posted),
    int(R.pending_transfer_already_voided),
    # the pending was resolved the other way by an earlier incarnation
    # (post raced a void or vice versa): closed either way
    int(R.exists_with_different_flags),
)


class _Leg:
    """One origin event's settlement in flight."""

    __slots__ = (
        "op", "ix", "origin_id", "beneficiary", "amount", "src", "dst",
        "phase", "void", "in_flight",
    )

    def __init__(self, op, ix, origin_id, beneficiary, amount, src, dst):
        self.op = op
        self.ix = ix
        self.origin_id = origin_id
        self.beneficiary = beneficiary
        self.amount = amount
        self.src = src
        self.dst = dst
        self.phase = "mirror"  # -> "resolve" -> "done"
        self.void = False
        self.in_flight = False


class HoldbackCursor:
    """Durable-cursor wrapper that defers persistence to the settlement
    watermark. The pump acks as it streams; this class stashes those
    acks and `release(watermark)` persists only the highest stashed op
    at or below the watermark — at-least-once redelivery of every op
    with unfinished legs is guaranteed across SIGKILL."""

    def __init__(self, inner):
        self.inner = inner
        self._stash: List[tuple] = []  # (op, checksum), ascending
        self._released = inner.load()[0]

    def load(self):
        return self.inner.load()

    def ack(self, op: int, checksum: int) -> None:
        if not self._stash or op > self._stash[-1][0]:
            self._stash.append((op, checksum))

    def release(self, watermark: int) -> None:
        best = None
        while self._stash and self._stash[0][0] <= watermark:
            best = self._stash.pop(0)
        if best is not None and best[0] > self._released:
            self.inner.ack(best[0], best[1])
            self._released = best[0]


class SettlementCore:
    """The agent's state machine (see module docstring). One instance
    per (origin region, federation); drivers may run one per region."""

    def __init__(
        self,
        topology: FederationTopology,
        region: int,
        window: int = 64,
        verifier=None,
        metrics=None,
        strict_gaps: bool = True,
    ):
        self.topology = topology
        self.region = region
        self.window = window
        self.verifier = verifier  # optional StreamVerifier fed every line
        self.strict_gaps = strict_gaps
        self.error: Optional[str] = None
        self._legs: Dict[tuple, _Leg] = {}  # (op, ix) -> unfinished leg
        self._ingested_op = 0  # staging high-water (intra-life dedup)
        self._last_seen_op = 0
        self.stats = {
            "outbound_seen": 0,
            "legs_posted": 0,
            "legs_voided": 0,
            "redeliveries": 0,
            "refusals": 0,
            "anomalies": 0,
        }
        self._metrics = None
        if metrics is not None:
            self._metrics = {
                "inflight": metrics.gauge("federation.inflight_legs"),
                "posted": metrics.counter("federation.legs_posted"),
                "voided": metrics.counter("federation.legs_voided"),
                "outbound": metrics.counter("federation.outbound_seen"),
                "refusals": metrics.counter("federation.sink_refusals"),
                "anomalies": metrics.counter("federation.anomalies"),
            }

    # -- sink protocol (called by the pump, one call per op) -----------

    def emit_lines(self, lines: Iterable[str]) -> bool:
        recs = [json.loads(ln) for ln in lines]
        if self.verifier is not None:
            for r in recs:
                self.verifier.feed(r)
        staged = []
        op = None
        for rec in recs:
            kind = rec.get("kind")
            if kind == "gap":
                if self.strict_gaps and self.error is None:
                    self.error = (
                        f"stream gap {rec.get('from')}..{rec.get('to')}: "
                        "origin history lost (run the origin with an AOF)"
                    )
                continue
            if kind != "transfer":
                continue
            op = int(rec["op"])
            if op <= self._ingested_op:
                self.stats["redeliveries"] += 1
                continue  # this life already staged it
            out = self.topology.classify_outbound(rec, self.region)
            if out is None:
                continue
            staged.append(_Leg(
                op=op,
                ix=int(rec["ix"]),
                origin_id=int(rec["id"]),
                beneficiary=out["beneficiary"],
                amount=out["amount"],
                src=self.region,
                dst=out["dst"],
            ))
        if staged and len(self._legs) + len(staged) > self.window:
            # refuse BEFORE staging: the pump retries the whole op once
            # the window drains — an accepted op is staged atomically
            self.stats["refusals"] += 1
            if self._metrics:
                self._metrics["refusals"].add()
            return False
        for leg in staged:
            self._legs[(leg.op, leg.ix)] = leg
        self.stats["outbound_seen"] += len(staged)
        if self._metrics:
            if staged:
                self._metrics["outbound"].add(len(staged))
            self._metrics["inflight"].set(len(self._legs))
        if op is not None:
            self._ingested_op = max(self._ingested_op, op)
            self._last_seen_op = max(self._last_seen_op, op)
        return True

    def flush(self) -> None:  # sink protocol (durability lives remote)
        pass

    # -- driver side: staged work --------------------------------------

    def dsts_with_work(self) -> Set[int]:
        return {
            leg.dst
            for leg in self._legs.values()
            if leg.phase == "mirror" and not leg.in_flight
        }

    def next_mirror_batch(self, dst: int, limit: int = 32) -> List[_Leg]:
        out = []
        for key in sorted(self._legs):
            leg = self._legs[key]
            if leg.phase == "mirror" and not leg.in_flight and leg.dst == dst:
                leg.in_flight = True
                out.append(leg)
                if len(out) >= limit:
                    break
        return out

    def mirror_transfers(self, legs: List[_Leg]) -> List[Transfer]:
        return [
            Transfer(
                id=settlement_id(leg.src, leg.op, leg.ix, 0),
                debit_account_id=self.topology.mirror(leg.dst, leg.src),
                credit_account_id=leg.beneficiary,
                amount=leg.amount,
                ledger=FEDERATION_LEDGER,
                code=SETTLE_CODE,
                user_data_128=leg.origin_id,
                user_data_64=leg.op,
                user_data_32=leg.ix,
            )
            for leg in legs
        ]

    def on_mirror_replies(self, legs: List[_Leg], codes: List[int]) -> None:
        for leg, code in zip(legs, codes):
            leg.in_flight = False
            if leg.phase != "mirror":
                continue
            leg.phase = "resolve"
            # any terminal rejection of the mirror (beneficiary missing,
            # flag/limit violations) voids the origin so the payer's
            # money comes back out of escrow — never stranded pending
            leg.void = int(code) not in _MIRROR_OK

    def next_resolve_batch(self, limit: int = 32) -> List[_Leg]:
        out = []
        for key in sorted(self._legs):
            leg = self._legs[key]
            if leg.phase == "resolve" and not leg.in_flight:
                leg.in_flight = True
                out.append(leg)
                if len(out) >= limit:
                    break
        return out

    def resolve_transfers(self, legs: List[_Leg]) -> List[Transfer]:
        return [
            Transfer(
                id=settlement_id(leg.src, leg.op, leg.ix, 1),
                pending_id=leg.origin_id,
                # amount 0 resolves the FULL pending amount (reference
                # post/void semantics), so redelivery after a partial
                # crash needs no amount bookkeeping
                amount=0,
                ledger=FEDERATION_LEDGER,
                code=SETTLE_CODE,
                flags=int(
                    TransferFlags.void_pending_transfer
                    if leg.void
                    else TransferFlags.post_pending_transfer
                ),
                user_data_64=leg.op,
                user_data_32=leg.ix,
            )
            for leg in legs
        ]

    def on_resolve_replies(self, legs: List[_Leg], codes: List[int]) -> None:
        for leg, code in zip(legs, codes):
            leg.in_flight = False
            if leg.phase != "resolve":
                continue
            if int(code) not in _RESOLVE_DONE:
                self.stats["anomalies"] += 1
                if self._metrics:
                    self._metrics["anomalies"].add()
            leg.phase = "done"
            key = "legs_voided" if leg.void else "legs_posted"
            self.stats[key] += 1
            if self._metrics:
                self._metrics["voided" if leg.void else "posted"].add()
            del self._legs[(leg.op, leg.ix)]
        if self._metrics:
            self._metrics["inflight"].set(len(self._legs))

    def on_request_failed(self, legs: List[_Leg]) -> None:
        """Client timeout/eviction: clear in-flight so the legs restage
        on the next driver turn (idempotent ids make the retry safe)."""
        for leg in legs:
            leg.in_flight = False

    # -- progress ------------------------------------------------------

    def pending_count(self) -> int:
        return len(self._legs)

    def idle(self) -> bool:
        return not self._legs

    def watermark(self) -> int:
        """Highest origin op whose staged legs have ALL resolved: the
        durable cursor may persist up to here and no further."""
        if not self._legs:
            return self._last_seen_op
        return min(op for op, _ix in self._legs) - 1
