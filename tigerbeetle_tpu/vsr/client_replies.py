"""ClientReplies: the latest reply per client, persisted in the dedicated
client_replies zone.

The reference stores one message-sized slot per client session
(reference: src/vsr/client_replies.zig; zone sizing clients_max x
message_size_max, src/vsr.zig:59-108), so a primary can answer a
duplicate request with the ORIGINAL reply bytes even after a restart —
without it, a retransmit arriving after recovery would have to be dropped
(re-executing is forbidden: exactly-once semantics).

A slot is validated on read against the reply checksum recorded in the
checkpointed client table: a torn write, a stale slot from an evicted
session, or bytes predating a state sync all fail the match and read as
absent (the caller falls back to its reply-lost path; the reference
additionally repairs reply slots from peers).
"""

from __future__ import annotations

from tigerbeetle_tpu.constants import ConfigCluster
from tigerbeetle_tpu.io.storage import Storage, Zone
from tigerbeetle_tpu.vsr.header import HEADER_SIZE, Command, Header


class ClientReplies:
    def __init__(self, storage: Storage, cluster: ConfigCluster):
        self.storage = storage
        self.slot_size = cluster.message_size_max
        # reply_slot_count, not clients_max: the ingress gateway's
        # many-session mode raises clients_max far past what a
        # slot-per-session zone could hold (constants.ConfigCluster)
        self.slot_count = cluster.reply_slot_count

    def write(self, slot: int, wire: bytes) -> None:
        """Best-effort persistence (write_lazy): a reply lost to a crash
        before the next sync reads as absent (checksum mismatch) and the
        reply-lost fallbacks apply; the checkpoint chain syncs before
        persisting the client table, so a checkpointed reply_checksum
        always has durable bytes behind it."""
        assert 0 <= slot < self.slot_count
        assert len(wire) <= self.slot_size
        self.storage.write_lazy(Zone.client_replies, slot * self.slot_size, wire)

    def read(self, slot: int, checksum: int) -> bytes | None:
        """The slot's reply wire bytes iff intact and matching `checksum`
        (the client table's record of which reply should be there)."""
        assert 0 <= slot < self.slot_count
        raw = self.storage.read(
            Zone.client_replies, slot * self.slot_size, self.slot_size
        )
        header = Header.from_bytes(raw[:HEADER_SIZE])
        if (
            not header.valid_checksum()
            or header.checksum != checksum
            or header.command != Command.reply
            or header.size > self.slot_size
        ):
            return None
        body = raw[HEADER_SIZE : header.size]
        if not header.valid_checksum_body(body):
            return None
        return raw[: header.size]
