"""Fault-tolerant cluster clock: Marzullo's algorithm over ping offsets.

The reference's design (reference: src/vsr/clock.zig:15-70,
src/vsr/marzullo.zig): each replica samples its clock offset against every
peer from ping/pong round trips — the peer's realtime was read somewhere
within the round trip, so the true offset lies in an interval
[t1 - m2, t1 - m0] (m0/m2 = own monotonic at send/receive, t1 = peer's
realtime). Marzullo's algorithm finds the smallest interval overlapping a
majority of sources (self included as [0,0]); its midpoint bounds the
cluster-synchronized wall time. `realtime_synchronized()` gates timestamp
assignment on having such a quorum window (reference:
src/vsr/replica.zig:1220-1223).
"""

from __future__ import annotations

import dataclasses

from tigerbeetle_tpu.io.time import Time


@dataclasses.dataclass
class Interval:
    lo: int
    hi: int
    sources: int = 0


def marzullo(intervals: list[tuple[int, int]], quorum: int) -> Interval | None:
    """Smallest interval contained in at least `quorum` of the input
    intervals (reference: src/vsr/marzullo.zig smallest_interval). Returns
    None if no point is covered by a quorum."""
    if not intervals:
        return None
    edges: list[tuple[int, int]] = []  # (offset, +1 open / -1 close)
    for lo, hi in intervals:
        assert lo <= hi
        edges.append((lo, -1))
        edges.append((hi, +1))
    # Sort by offset; opens (-1) before closes (+1) at the same offset.
    edges.sort()
    best: Interval | None = None
    count = 0
    lo = None
    for offset, kind in edges:
        if kind == -1:
            count += 1
            if count >= quorum and (best is None or count > best.sources):
                lo = offset
                best = Interval(lo=offset, hi=offset, sources=count)
        else:
            if best is not None and best.sources == count and lo is not None:
                best.hi = offset
                lo = None
            count -= 1
    if best is None or best.sources < quorum:
        return None
    return best


class Clock:
    """Per-replica clock state; fed by the replica's ping/pong traffic.
    Samples expire (the reference re-samples in windowed epochs, reference:
    src/vsr/clock.zig epoch handling) so a long-dead peer's stale offset
    cannot keep steering the synchronized time."""

    def __init__(self, replica: int, replica_count: int, time: Time,
                 sample_age_max_ns: int = 10_000_000_000):
        self.replica = replica
        self.replica_count = replica_count
        self.time = time
        self.sample_age_max_ns = sample_age_max_ns
        # Freshest offset interval per peer + the monotonic time it was
        # taken (self is implicit [0, 0], never stale).
        self.samples: dict[int, tuple[int, int, int]] = {}

    @property
    def quorum(self) -> int:
        return self.replica_count // 2 + 1

    # -- sampling (driven by the replica's pong handler) --

    def learn(self, peer: int, m0: int, t1: int, m2: int) -> None:
        """A pong round trip: own monotonic m0 at ping send, peer realtime
        t1, own monotonic m2 at pong receive."""
        if peer == self.replica or m2 < m0:
            return  # m2 == m0 is a zero-width (exact) interval — valid
        # The peer read t1 somewhere in [m0, m2]: offset in [t1-m2, t1-m0],
        # expressed relative to our realtime at the midpoint.
        own_realtime = self.time.realtime()
        own_monotonic = self.time.monotonic()
        # Project both bounds to "peer_realtime - own_realtime" offsets.
        base = own_realtime - own_monotonic
        self.samples[peer] = (t1 - (base + m2), t1 - (base + m0), own_monotonic)

    def _window(self) -> Interval | None:
        now = self.time.monotonic()
        fresh = [
            (lo, hi)
            for lo, hi, taken in self.samples.values()
            if now - taken <= self.sample_age_max_ns
        ]
        return marzullo([(0, 0)] + fresh, self.quorum)

    # -- reading --

    def realtime(self) -> int:
        return self.time.realtime()

    def realtime_synchronized(self) -> int | None:
        """Cluster-synchronized wall time, or None when no quorum of FRESH
        samples exists (timestamp assignment must wait)."""
        window = self._window()
        if window is None:
            return None
        midpoint = (window.lo + window.hi) // 2
        return self.time.realtime() + midpoint
