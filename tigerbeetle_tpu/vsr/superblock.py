"""The SuperBlock: the durable VSR root, 4 redundant copies with quorum.

The reference's design (reference: src/vsr/superblock.zig:1-34): the
superblock records the durable `vsr_state` — checkpoint op (commit_min),
its checksum, view numbers — plus references to the checkpoint's trailers.
Here the trailers are the device-ledger snapshot blobs living in the grid
zone (ping-ponged by sequence parity so the previous checkpoint stays
intact while the next one writes — the reference's copy-on-write manifest
serves the same purpose).

4 copies are written per checkpoint (reference: superblock_copies=4,
src/config.zig:138); opening requires a quorum of >= 2 valid copies of the
winning sequence (reference: src/vsr/superblock_quorums.zig), so a crash
torn mid-update (some copies new, some old) resolves to whichever sequence
has quorum — and because copies are written new-sequence-last-synced-first,
at least one complete set survives.
"""

from __future__ import annotations

import dataclasses
import json

from tigerbeetle_tpu import native
from tigerbeetle_tpu.io.storage import Storage, Zone, ZoneLayout

MAGIC = 0x7475_5F74_6267_6C62  # "tbgl_tpu" as a tag
QUORUM = 2


@dataclasses.dataclass
class BlobRef:
    """A checkpoint trailer blob in the grid zone."""

    name: str
    offset: int  # grid-zone logical offset
    size: int
    checksum: int


@dataclasses.dataclass
class VSRState:
    """Durable consensus + checkpoint state (reference:
    src/vsr/superblock.zig vsr_state)."""

    cluster: int = 0
    replica: int = 0
    sequence: int = 0  # superblock version counter
    commit_min: int = 0  # checkpoint op: state <= this op is in the snapshot
    commit_min_checksum: int = 0  # hash-chain anchor for replay
    commit_max: int = 0
    view: int = 0
    log_view: int = 0
    prepare_timestamp: int = 0
    area: int = 0  # grid area holding `blobs` (explicit ping-pong side)
    blobs: list[BlobRef] = dataclasses.field(default_factory=list)
    meta: dict = dataclasses.field(default_factory=dict)  # small host state

    def to_bytes(self) -> bytes:
        d = dataclasses.asdict(self)
        return json.dumps(d, sort_keys=True).encode()

    @staticmethod
    def from_bytes(b: bytes) -> "VSRState":
        d = json.loads(b.decode())
        d["blobs"] = [BlobRef(**x) for x in d["blobs"]]
        return VSRState(**d)


class SuperBlock:
    """Serialized copy layout (one per 64 KiB copy slot):
    [0:8)   magic
    [8:16)  payload length
    [16:32) payload checksum (AEGIS-128L)
    [32:..) payload (VSRState bytes)
    """

    def __init__(self, storage: Storage):
        self.storage = storage
        self.layout = storage.layout
        self.state: VSRState | None = None

    def _copy_bytes(self, state: VSRState) -> bytes:
        payload = state.to_bytes()
        assert len(payload) + 32 <= ZoneLayout.SUPERBLOCK_COPY_SIZE, (
            "superblock payload overflow"
        )
        head = (
            MAGIC.to_bytes(8, "little")
            + len(payload).to_bytes(8, "little")
            + native.checksum(payload).to_bytes(16, "little")
        )
        return head + payload

    def checkpoint(self, state: VSRState) -> None:
        """Durably advance to `state` (sequence must increase)."""
        if self.state is not None:
            assert state.sequence > self.state.sequence
        blob = self._copy_bytes(state)
        for copy in range(ZoneLayout.SUPERBLOCK_COPIES):
            self.storage.write(
                Zone.superblock, copy * ZoneLayout.SUPERBLOCK_COPY_SIZE, blob
            )
            # Sync after the FIRST copy so at least one complete new copy is
            # durable before the rest overwrite old ones, and after the last.
            if copy in (0, ZoneLayout.SUPERBLOCK_COPIES - 1):
                self.storage.sync()
        self.state = state

    @staticmethod
    def decode_copy(raw: bytes) -> tuple[VSRState | None, str]:
        """Decode ONE copy's raw bytes -> (state, verdict). The single
        implementation of the copy wire format, shared by the quorum
        open and `tigerbeetle inspect superblock` (which reports every
        copy's verdict instead of silently skipping the bad ones)."""
        if int.from_bytes(raw[0:8], "little") != MAGIC:
            return None, "bad magic"
        length = int.from_bytes(raw[8:16], "little")
        if length + 32 > len(raw):
            return None, "length overflows the copy"
        want = int.from_bytes(raw[16:32], "little")
        payload = raw[32 : 32 + length]
        if native.checksum(payload) != want:
            return None, "payload checksum mismatch"
        return VSRState.from_bytes(payload), "valid"

    @staticmethod
    def quorum_winner(
        states: list[VSRState | None],
    ) -> tuple[VSRState | None, int]:
        """The quorum rule in ONE place (shared with `tigerbeetle
        inspect`, which must report the same winner the replica would
        open): (winning state, number of copies carrying it), or
        (None, 0) when no sequence reaches QUORUM valid copies."""
        by_seq: dict[int, int] = {}
        by_state: dict[int, VSRState] = {}
        for st in states:
            if st is None:
                continue
            by_seq[st.sequence] = by_seq.get(st.sequence, 0) + 1
            by_state[st.sequence] = st
        quorate = [s for s, n in by_seq.items() if n >= QUORUM]
        if not quorate:
            return None, 0
        winner = max(quorate)
        return by_state[winner], by_seq[winner]

    def open(self) -> VSRState:
        """Quorum read: the highest sequence with >= QUORUM valid copies."""
        decoded = [
            self.decode_copy(self.storage.read(
                Zone.superblock,
                copy * ZoneLayout.SUPERBLOCK_COPY_SIZE,
                ZoneLayout.SUPERBLOCK_COPY_SIZE,
            ))[0]
            for copy in range(ZoneLayout.SUPERBLOCK_COPIES)
        ]
        state, _copies = self.quorum_winner(decoded)
        if state is None:
            raise RuntimeError(
                "superblock: no sequence with a quorum of valid copies "
                "— data file corrupt or not formatted"
            )
        self.state = state
        return self.state
