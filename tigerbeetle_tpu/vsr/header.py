"""The 128-byte VSR Header — shared by network messages and journal entries.

Field-for-field the reference's wire layout (reference: src/vsr.zig:235-394:
checksum u128, checksum_body u128, parent u128, client u128, context u128,
request u32, cluster u32, epoch u32, view u32, op u64, commit u64,
timestamp u64, size u32, replica u8, command u8, operation u8, version u8 —
little-endian extern struct, no padding). The dual checksums let a header be
trusted without reading its body, and `parent` hash-chains prepares
(reference: src/vsr.zig:246-268).

Checksums are the native AEGIS-128L MAC (tigerbeetle_tpu.native), identical
construction to the reference's vsr.checksum.
"""

from __future__ import annotations

import dataclasses
import enum
import struct

import numpy as np

from tigerbeetle_tpu import native
from tigerbeetle_tpu.types import Operation

HEADER_SIZE = 128
VERSION = 0

# One struct pack/unpack per (de)serialization: the numpy-record path cost
# ~30 us per call and every message pays several (receive parse, checksum
# verify, send serialize) — at wire rate that is real event-loop time.
# u128 fields travel as (lo, hi) u64 pairs, same little-endian layout.
_WIRE = struct.Struct("<10Q4I3QI4B")
assert _WIRE.size == HEADER_SIZE
_U64 = 0xFFFFFFFFFFFFFFFF


def trace_id(client: int, request_checksum: int) -> int:
    """Cluster-causal trace id: a u64 derived DETERMINISTICALLY from
    (client id, request checksum) — the pair that uniquely names one
    client request cluster-wide — so every process that sees any leg of
    the op derives the SAME id with no coordination, and the simulator's
    traces stay byte-reproducible (no RNG, no wall clock).

    The carrier is the header's `context` field: the primary already
    stamps every prepare with context = the request's checksum (the
    reserved use of context on the prepare/reply path), so prepares,
    journal slots, replies and CDC records all carry enough to re-derive
    the id — the trace identity propagates with the consensus stream
    itself, costing zero extra wire bytes. splitmix64 finalizers over
    the folded u128s — the client mixes BEFORE the checksum folds in, so
    the derivation is not symmetric in its arguments: cheap, well-mixed,
    pure int math."""
    c = (client ^ (client >> 64)) & _U64
    s = (request_checksum ^ (request_checksum >> 64)) & _U64
    x = (c + 0x9E3779B97F4A7C15) & _U64
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9 & _U64
    x = (x ^ (x >> 27)) * 0x94D049BB133111EB & _U64
    x = (x ^ (x >> 31)) ^ s
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9 & _U64
    x = (x ^ (x >> 27)) * 0x94D049BB133111EB & _U64
    x ^= x >> 31
    # never 0: 0 is the "untraced" sentinel in span args
    return x or 1


class Command(enum.IntEnum):
    """VSR protocol commands (reference: src/vsr.zig:111-154)."""

    reserved = 0
    ping = 1
    pong = 2
    ping_client = 3
    pong_client = 4
    request = 5
    prepare = 6
    prepare_ok = 7
    reply = 8
    commit = 9
    start_view_change = 10
    do_view_change = 11
    start_view = 12
    request_start_view = 13
    request_headers = 14
    request_prepare = 15
    request_reply = 16
    headers = 17
    eviction = 18
    request_blocks = 19
    block = 20
    request_sync_manifest = 21
    request_sync_free_set = 22
    request_sync_client_sessions = 23
    sync_manifest = 24
    sync_free_set = 25
    sync_client_sessions = 26
    # Ingress extension (tigerbeetle_tpu/ingress): a typed load-shed
    # reply. The gateway answers a request it cannot admit (saturated
    # commit pipeline / exhausted message pool / session table full) with
    # `busy` echoing the client + request number — the client backs off
    # and retries, instead of timing out against a silent drop.
    busy = 27
    # Live introspection (`tigerbeetle inspect live`, inspect.py): a
    # request_stats frame asks a running replica for its [stats]-registry
    # snapshot + basic consensus state; the `stats` reply carries the
    # JSON body. Served in ANY status — the whole point is to look at a
    # replica that is wedged mid-view-change or mid-recovery.
    request_stats = 28
    stats = 29
    # Phase marker (scripts/prodday.py, inspect.send_mark): a `mark`
    # frame carries a phase name in its body; the replica stamps it into
    # its flight recorder so per-interval history slices by scenario
    # phase. Served in ANY status (a driver marks phases through faults)
    # and answered with a small `stats` ack so the driver knows the
    # boundary landed before offered load changes.
    mark = 30


# Vectorized view of the same layout (batch scans over header rings);
# cross-checked against _WIRE below so the two definitions cannot drift.
HEADER_DTYPE = np.dtype(
    [
        ("checksum_lo", "<u8"), ("checksum_hi", "<u8"),
        ("checksum_body_lo", "<u8"), ("checksum_body_hi", "<u8"),
        ("parent_lo", "<u8"), ("parent_hi", "<u8"),
        ("client_lo", "<u8"), ("client_hi", "<u8"),
        ("context_lo", "<u8"), ("context_hi", "<u8"),
        ("request", "<u4"),
        ("cluster", "<u4"),
        ("epoch", "<u4"),
        ("view", "<u4"),
        ("op", "<u8"),
        ("commit", "<u8"),
        ("timestamp", "<u8"),
        ("size", "<u4"),
        ("replica", "u1"),
        ("command", "u1"),
        ("operation", "u1"),
        ("version", "u1"),
    ]
)
assert HEADER_DTYPE.itemsize == HEADER_SIZE


@dataclasses.dataclass
class Header:
    checksum: int = 0
    checksum_body: int = 0
    parent: int = 0
    client: int = 0
    context: int = 0
    request: int = 0
    cluster: int = 0
    epoch: int = 0
    view: int = 0
    op: int = 0
    commit: int = 0
    timestamp: int = 0
    size: int = HEADER_SIZE
    replica: int = 0
    command: int = int(Command.reserved)
    operation: int = int(Operation.reserved)
    version: int = VERSION

    # -- wire --

    def to_bytes(self) -> bytes:
        return _WIRE.pack(
            self.checksum & _U64, self.checksum >> 64,
            self.checksum_body & _U64, self.checksum_body >> 64,
            self.parent & _U64, self.parent >> 64,
            self.client & _U64, self.client >> 64,
            self.context & _U64, self.context >> 64,
            self.request, self.cluster, self.epoch, self.view,
            self.op, self.commit, self.timestamp,
            self.size, self.replica, self.command, self.operation,
            self.version,
        )

    @staticmethod
    def from_bytes(b) -> "Header":
        assert len(b) == HEADER_SIZE, len(b)
        v = _WIRE.unpack(b)
        return Header(
            checksum=v[0] | (v[1] << 64),
            checksum_body=v[2] | (v[3] << 64),
            parent=v[4] | (v[5] << 64),
            client=v[6] | (v[7] << 64),
            context=v[8] | (v[9] << 64),
            request=v[10], cluster=v[11], epoch=v[12], view=v[13],
            op=v[14], commit=v[15], timestamp=v[16],
            size=v[17], replica=v[18], command=v[19], operation=v[20],
            version=v[21],
        )

    # -- tracing --

    def trace(self) -> int:
        """The op's cluster-causal trace id, derived from the fields THIS
        header carries: a request hashes its own checksum (ingress — the
        id is assigned here); prepares and replies carry the request
        checksum in `context` (see trace_id). Only meaningful for
        request/prepare/reply-shaped headers."""
        if self.command == int(Command.request):
            return trace_id(self.client, self.checksum)
        return trace_id(self.client, self.context)

    # -- checksums (reference: src/vsr.zig:428-442 set/valid pattern) --

    def calculate_checksum(self) -> int:
        """Checksum over the header bytes EXCLUDING the leading checksum
        field itself."""
        return native.checksum(self.to_bytes()[16:])

    def set_checksum_body(self, body: bytes) -> None:
        self.size = HEADER_SIZE + len(body)
        self.checksum_body = native.checksum(body)

    def set_checksum(self) -> None:
        self.checksum = self.calculate_checksum()

    def valid_checksum(self) -> bool:
        return self.checksum == self.calculate_checksum()

    def valid_checksum_body(self, body: bytes) -> bool:
        return self.checksum_body == native.checksum(body)


# _WIRE and HEADER_DTYPE define the same 128-byte layout twice (struct for
# scalar speed, dtype for vectorized ring scans): pin them together so an
# edit to one cannot silently drift from the other.
_probe = np.frombuffer(
    Header(
        checksum=(1 << 64) | 2, checksum_body=3, parent=4, client=5,
        context=6, request=7, cluster=8, epoch=9, view=10, op=11, commit=12,
        timestamp=13, size=14, replica=15, command=16, operation=17,
        version=18,
    ).to_bytes(),
    dtype=HEADER_DTYPE,
)[0]
assert (
    (int(_probe["checksum_lo"]), int(_probe["checksum_hi"])) == (2, 1)
    and int(_probe["checksum_body_lo"]) == 3
    and int(_probe["context_lo"]) == 6
    and int(_probe["request"]) == 7
    and int(_probe["view"]) == 10
    and int(_probe["op"]) == 11
    and int(_probe["timestamp"]) == 13
    and int(_probe["size"]) == 14
    and int(_probe["replica"]) == 15
    and int(_probe["command"]) == 16
    and int(_probe["version"]) == 18
), "Header _WIRE struct and HEADER_DTYPE layouts diverged"
del _probe
