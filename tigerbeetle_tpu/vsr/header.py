"""The 128-byte VSR Header — shared by network messages and journal entries.

Field-for-field the reference's wire layout (reference: src/vsr.zig:235-394:
checksum u128, checksum_body u128, parent u128, client u128, context u128,
request u32, cluster u32, epoch u32, view u32, op u64, commit u64,
timestamp u64, size u32, replica u8, command u8, operation u8, version u8 —
little-endian extern struct, no padding). The dual checksums let a header be
trusted without reading its body, and `parent` hash-chains prepares
(reference: src/vsr.zig:246-268).

Checksums are the native AEGIS-128L MAC (tigerbeetle_tpu.native), identical
construction to the reference's vsr.checksum.
"""

from __future__ import annotations

import dataclasses
import enum

import numpy as np

from tigerbeetle_tpu import native
from tigerbeetle_tpu.types import Operation, join_u128, split_u128

HEADER_SIZE = 128
VERSION = 0


class Command(enum.IntEnum):
    """VSR protocol commands (reference: src/vsr.zig:111-154)."""

    reserved = 0
    ping = 1
    pong = 2
    ping_client = 3
    pong_client = 4
    request = 5
    prepare = 6
    prepare_ok = 7
    reply = 8
    commit = 9
    start_view_change = 10
    do_view_change = 11
    start_view = 12
    request_start_view = 13
    request_headers = 14
    request_prepare = 15
    request_reply = 16
    headers = 17
    eviction = 18
    request_blocks = 19
    block = 20
    request_sync_manifest = 21
    request_sync_free_set = 22
    request_sync_client_sessions = 23
    sync_manifest = 24
    sync_free_set = 25
    sync_client_sessions = 26


HEADER_DTYPE = np.dtype(
    [
        ("checksum_lo", "<u8"), ("checksum_hi", "<u8"),
        ("checksum_body_lo", "<u8"), ("checksum_body_hi", "<u8"),
        ("parent_lo", "<u8"), ("parent_hi", "<u8"),
        ("client_lo", "<u8"), ("client_hi", "<u8"),
        ("context_lo", "<u8"), ("context_hi", "<u8"),
        ("request", "<u4"),
        ("cluster", "<u4"),
        ("epoch", "<u4"),
        ("view", "<u4"),
        ("op", "<u8"),
        ("commit", "<u8"),
        ("timestamp", "<u8"),
        ("size", "<u4"),
        ("replica", "u1"),
        ("command", "u1"),
        ("operation", "u1"),
        ("version", "u1"),
    ]
)
assert HEADER_DTYPE.itemsize == HEADER_SIZE


@dataclasses.dataclass
class Header:
    checksum: int = 0
    checksum_body: int = 0
    parent: int = 0
    client: int = 0
    context: int = 0
    request: int = 0
    cluster: int = 0
    epoch: int = 0
    view: int = 0
    op: int = 0
    commit: int = 0
    timestamp: int = 0
    size: int = HEADER_SIZE
    replica: int = 0
    command: int = int(Command.reserved)
    operation: int = int(Operation.reserved)
    version: int = VERSION

    # -- wire --

    def to_bytes(self) -> bytes:
        row = np.zeros(1, dtype=HEADER_DTYPE)[0]
        for f in ("checksum", "checksum_body", "parent", "client", "context"):
            lo, hi = split_u128(getattr(self, f))
            row[f + "_lo"], row[f + "_hi"] = lo, hi
        for f in ("request", "cluster", "epoch", "view", "op", "commit",
                  "timestamp", "size", "replica", "command", "operation",
                  "version"):
            row[f] = getattr(self, f)
        return row.tobytes()

    @staticmethod
    def from_bytes(b: bytes) -> "Header":
        assert len(b) == HEADER_SIZE, len(b)
        row = np.frombuffer(b, dtype=HEADER_DTYPE)[0]
        h = Header()
        for f in ("checksum", "checksum_body", "parent", "client", "context"):
            setattr(h, f, join_u128(row[f + "_lo"], row[f + "_hi"]))
        for f in ("request", "cluster", "epoch", "view", "op", "commit",
                  "timestamp", "size", "replica", "command", "operation",
                  "version"):
            setattr(h, f, int(row[f]))
        return h

    # -- checksums (reference: src/vsr.zig:428-442 set/valid pattern) --

    def calculate_checksum(self) -> int:
        """Checksum over the header bytes EXCLUDING the leading checksum
        field itself."""
        return native.checksum(self.to_bytes()[16:])

    def set_checksum_body(self, body: bytes) -> None:
        self.size = HEADER_SIZE + len(body)
        self.checksum_body = native.checksum(body)

    def set_checksum(self) -> None:
        self.checksum = self.calculate_checksum()

    def valid_checksum(self) -> bool:
        return self.checksum == self.calculate_checksum()

    def valid_checksum_body(self, body: bytes) -> bool:
        return self.checksum_body == native.checksum(body)
