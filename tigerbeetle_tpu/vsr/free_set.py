"""Grid block allocator: a bitset free set with reservations and an EWAH
trailer encoding (reference: src/vsr/superblock_free_set.zig:14-23
Reservations, :10 EWAH trailer encoding). The grid block store that will
persist this trailer through the superblock is not built yet — encode()/
decode() are its wire format.

Blocks are addressed 1..block_count (address 0 is reserved/null, like the
reference). A Reservation pins a range of potentially-free blocks so that
concurrent compactions can acquire from disjoint windows deterministically;
outstanding reservations exclude their windows from later reserve() scans.
"""

from __future__ import annotations

import dataclasses

from tigerbeetle_tpu.stdx import ewah_decode, ewah_encode

_WORD = 64


@dataclasses.dataclass
class Reservation:
    block_base: int  # first block index (0-based) of the window
    block_count: int
    session: int


class FreeSet:
    def __init__(self, block_count: int):
        assert block_count % _WORD == 0
        self.block_count = block_count
        # bit SET = block free (index 0 = address 1)
        self.words = [(1 << _WORD) - 1] * (block_count // _WORD)
        self.reservation_count = 0
        self.reservation_session = 1
        self._reserved_hi = 0  # blocks below this are in a live reservation

    # -- bit helpers --

    def is_free(self, address: int) -> bool:
        i = address - 1
        return bool(self.words[i // _WORD] >> (i % _WORD) & 1)

    def _set(self, i: int, free: bool) -> None:
        if free:
            self.words[i // _WORD] |= 1 << (i % _WORD)
        else:
            self.words[i // _WORD] &= ~(1 << (i % _WORD))

    def count_free(self) -> int:
        return sum(bin(w).count("1") for w in self.words)

    # -- reservations (reference: reserve/forfeit discipline) --

    def reserve(self, count: int) -> Reservation | None:
        """Reserve a window containing >= count free blocks. The scan starts
        past every outstanding reservation's window, so concurrent holders
        get DISJOINT windows (the contract concurrent compactions rely on;
        reference: superblock_free_set.zig reservation discipline)."""
        free_seen = 0
        base = None
        for i in range(self._reserved_hi, self.block_count):
            if self.words[i // _WORD] >> (i % _WORD) & 1:
                if base is None:
                    base = i
                free_seen += 1
                if free_seen == count:
                    self.reservation_count += 1
                    self._reserved_hi = i + 1
                    return Reservation(
                        block_base=base, block_count=i - base + 1,
                        session=self.reservation_session,
                    )
        return None

    def forfeit(self, reservation: Reservation) -> None:
        assert reservation.session == self.reservation_session
        self.reservation_count -= 1
        if self.reservation_count == 0:
            self.reservation_session += 1  # stale reservations now assert
            self._reserved_hi = 0

    def acquire(self, reservation: Reservation) -> int | None:
        """First free block within the reservation window -> address."""
        assert reservation.session == self.reservation_session
        for i in range(
            reservation.block_base,
            reservation.block_base + reservation.block_count,
        ):
            if self.words[i // _WORD] >> (i % _WORD) & 1:
                self._set(i, False)
                return i + 1
        return None

    def release(self, address: int) -> None:
        i = address - 1
        assert not self.is_free(address), f"double free of block {address}"
        self._set(i, True)

    # -- superblock trailer encoding --

    def encode(self) -> bytes:
        return ewah_encode(self.words)

    @classmethod
    def decode(cls, data: bytes, block_count: int) -> "FreeSet":
        fs = cls(block_count)
        fs.words = ewah_decode(data, block_count // _WORD)
        return fs
