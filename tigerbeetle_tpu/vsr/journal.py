"""The write-ahead log: two on-disk circular rings over the Storage seam.

The reference's journal design (reference: src/vsr/journal.zig:18-47): a
`wal_prepares` ring of `journal_slot_count` message-sized slots holding the
full prepare (header + body), plus a redundant `wal_headers` ring holding
only the 128-byte headers. The redundant copy disambiguates torn writes: a
torn PREPARE write leaves a valid redundant header pointing at a broken
prepare (slot faulty, repairable); a torn HEADER write leaves a valid
prepare whose own header wins (reference: src/vsr/journal.zig:374-535
recovery decision matrix — the single-replica subset implemented here).

Slot assignment: op % slot_count (ring). The checkpoint interval keeps a
bar of headroom so un-checkpointed ops are never overwritten (reference:
src/vsr.zig:2003-2035 checkpoint arithmetic).
"""

from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from time import perf_counter_ns

from tigerbeetle_tpu.constants import ConfigCluster
from tigerbeetle_tpu.io.storage import SECTOR_SIZE, Storage, Zone
from tigerbeetle_tpu.metrics import NULL_METRICS
from tigerbeetle_tpu.tracer import NULL_TRACER
from tigerbeetle_tpu.vsr.header import HEADER_SIZE, Command, Header


class Journal:
    # observability seams — the owning replica (or the composition root)
    # re-points these at its shared registry/tracer; defaults are the
    # zero-cost no-op backends
    metrics = NULL_METRICS
    tracer = NULL_TRACER

    def __init__(self, storage: Storage, cluster: ConfigCluster):
        # crossed by the writer pool, but every concurrent write targets
        # a disjoint region: prepare slots are op-owned, shared header
        # SECTORS serialize through _sector_locks, and evidence surgery
        # (invalidate_above/recover) quiesces the pool first
        self.storage = storage  # vet: handoff
        self.cluster = cluster
        self.slot_count = cluster.journal_slot_count
        self.msg_max = cluster.message_size_max
        # In-memory mirror of the redundant header ring (so a slot's header
        # write is a single-sector read-modify-write against this mirror).
        self._headers = bytearray(self.slot_count * HEADER_SIZE)
        # Async write path (reference: journal IOPS pools, 8 write iops,
        # src/config.zig:97-98): a small writer pool overlaps the 1 MiB
        # O_DSYNC prepare writes with device commits and other requests.
        # Created lazily — deterministic tests never touch it.
        self._executor: ThreadPoolExecutor | None = None
        self._sector_locks: dict[int, threading.Lock] = {}
        self._locks_guard = threading.Lock()
        # add() on the event loop, discard() on the completing worker via
        # add_done_callback — both GIL-atomic set ops; quiesce() snapshots
        # with list() before iterating (join-before-read)
        self._pending_writes: set[Future] = set()  # vet: handoff
        # Durable-header mirror: a slot's header enters this mirror (and
        # therefore reaches the redundant ring on disk) only AFTER its own
        # prepare write completed — a neighbor slot's sector write must
        # never publish a header whose prepare is still in flight (the
        # prepare-before-header ordering contract, per slot). Worker
        # writes hold the slot's sector lock; event-loop writes happen
        # only on the sync path (no pool) or after quiesce()
        self._headers_durable = bytearray(  # vet: handoff
            self.slot_count * HEADER_SIZE
        )

    def slot_for_op(self, op: int) -> int:
        return op % self.slot_count

    # -- write path --

    def write_prepare(self, header: Header, body: bytes) -> None:
        """Write prepare (header+body) to its slot, then the redundant
        header — prepare FIRST, matching the reference's ordering so a torn
        redundant-header write still recovers from the prepare ring
        (reference: src/vsr/journal.zig write_prepare_header sequencing)."""
        assert header.command == Command.prepare
        assert header.size == HEADER_SIZE + len(body)
        assert header.size <= self.msg_max
        slot = self.slot_for_op(header.op)
        with self.tracer.span(
            "journal.write_prepare", op=header.op,
            trace=header.trace() if self.tracer.enabled else 0,
        ), self.metrics.histogram("journal.write_us").time():
            self.storage.write(
                Zone.wal_prepares, slot * self.msg_max,
                header.to_bytes() + body,
            )
            self._write_header(slot, header)
        self.metrics.counter("journal.writes").add()
        from tigerbeetle_tpu import constants

        if constants.VERIFY:
            # intensive tier: read-after-write — the slot must round-trip
            # through the storage seam with both checksums intact
            got = self.read_prepare(header.op)
            assert got is not None and got[0].checksum == header.checksum, (
                f"VERIFY: prepare op {header.op} failed read-after-write"
            )

    def _write_header(self, slot: int, header: Header) -> None:
        off = slot * HEADER_SIZE
        wire = header.to_bytes()
        self._headers[off : off + HEADER_SIZE] = wire
        self._headers_durable[off : off + HEADER_SIZE] = wire
        self._write_header_sector(off // SECTOR_SIZE * SECTOR_SIZE)

    def _write_header_sector(self, sector: int) -> None:
        self.storage.write(
            Zone.wal_headers, sector,
            bytes(self._headers_durable[sector : sector + SECTOR_SIZE]),
        )

    # -- async write path (the reply/ack waits on the future; everything
    # else overlaps: reference journal write IOPS, src/config.zig:97-98) --

    def write_prepare_async(self, header: Header, body: bytes) -> Future:
        """Mirror-update now (synchronously — evidence scans see the op
        immediately); the durable prepare + header-sector writes run on
        the writer pool. The caller MUST await the future before acking
        (prepare_ok / client reply): WAL-before-ack is the contract."""
        assert header.command == Command.prepare
        assert header.size == HEADER_SIZE + len(body)
        slot = self.slot_for_op(header.op)
        off = slot * HEADER_SIZE
        hb = header.to_bytes()
        self._headers[off : off + HEADER_SIZE] = hb
        sector = off // SECTOR_SIZE * SECTOR_SIZE
        if self._executor is None:
            self._executor = ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="journal"
            )
        # header and body ship separately: the 1 MiB header+body concat
        # happens on the WRITER thread, not the event loop (a measured
        # per-batch copy on the reply-serving core). The trace id is
        # derived HERE (event loop, header in hand) and handed to the
        # worker as a plain int for its span tag.
        tid = header.trace() if self.tracer.enabled else 0
        fut = self._executor.submit(
            # submit stamp for the WAL parallel lane (latency.py): the
            # reply only waits on the RESIDUAL of this write at finalize,
            # so its full submit->durable time is invisible to the
            # critical-path legs — latency.wal_lane_us carries it
            self._write_task, slot, sector, hb, body, tid,
            perf_counter_ns(),
        )
        self._pending_writes.add(fut)
        fut.add_done_callback(self._pending_writes.discard)
        return fut

    def quiesce(self) -> None:
        """Wait for every in-flight async prepare write. Evidence surgery
        (invalidate_above) and recovery-order-sensitive transitions must
        not race a queued write that would re-populate a zeroed slot."""
        for fut in list(self._pending_writes):
            fut.result()

    def submit_io(self, fn, *args) -> Future:
        """FIFO background IO (client-reply slot writes): one worker, so
        successive writes to the same slot land in submission order."""
        if getattr(self, "_io_executor", None) is None:
            self._io_executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="journal-io"
            )
            # same discipline as _pending_writes: GIL-atomic add/discard,
            # drain_io() snapshots with list() (join-before-read)
            self._pending_io: set[Future] = set()  # vet: handoff
        fut = self._io_executor.submit(fn, *args)
        self._pending_io.add(fut)
        fut.add_done_callback(self._pending_io.discard)
        return fut

    def drain_io(self) -> None:
        """Wait for queued background IO. The checkpoint chain must call
        this before persisting the client table: a recorded reply_checksum
        whose slot write never landed would wedge that session forever
        (duplicate requests dropped, reply unreadable)."""
        for fut in list(getattr(self, "_pending_io", ())):
            fut.result()

    def _write_task(self, slot: int, sector: int, hb: bytes,
                    body: bytes, tid: int = 0, t_submit: int = 0) -> None:
        # prepare FIRST, then the redundant header (same ordering contract
        # as the sync path). Concurrent slots may share a header sector:
        # a slot's header enters the DURABLE mirror only here — after its
        # own prepare landed — so a neighbor's sector write can never
        # publish a header whose prepare is still in flight.
        with self.tracer.span("journal.write_prepare", slot=slot,
                              trace=tid), \
                self.metrics.histogram("journal.write_us").time():
            self.storage.write(
                Zone.wal_prepares, slot * self.msg_max, hb + body
            )
            off = slot * HEADER_SIZE
            with self._locks_guard:
                lock = self._sector_locks.setdefault(
                    sector, threading.Lock()
                )
            with lock:
                self._headers_durable[off : off + HEADER_SIZE] = hb
                self._write_header_sector(sector)
        self.metrics.counter("journal.writes").add()
        if t_submit:
            # WAL lane: event-loop submit -> durable (queue wait + the
            # 1 MiB O_DSYNC write), observed on the writer thread
            self.metrics.histogram("latency.wal_lane_us").observe(
                (perf_counter_ns() - t_submit) / 1000.0
            )

    def invalidate_above(self, op_max: int) -> None:
        """Destroy journal evidence for every op above `op_max` — BOTH the
        header-mirror/redundant ring and the prepare ring.

        Called when a view change completes: the quorum decided the log
        ends at `op_max`, so any surviving slot above it holds a superseded
        prepare from an abandoned view. Left in place, the next
        _dvc_suffix_headers scan would re-advertise those headers under
        this replica's NEW log_view, where best-log merging treats them as
        authoritative — a truncated prepare could be resurrected and shadow
        the op committed in the intervening view (replica divergence). The
        disk writes make the invalidation survive a restart (recover()
        would otherwise rebuild the mirror from the stale rings)."""
        # An in-flight async write for a superseded op would land AFTER
        # the zeroing below and resurrect the evidence: drain first.
        self.quiesce()
        for slot in range(self.slot_count):
            off = slot * HEADER_SIZE
            h = Header.from_bytes(bytes(self._headers[off : off + HEADER_SIZE]))
            if not (h.valid_checksum() and h.command == Command.prepare):
                continue
            if h.op <= op_max:
                continue
            self._headers[off : off + HEADER_SIZE] = bytes(HEADER_SIZE)
            self._headers_durable[off : off + HEADER_SIZE] = bytes(HEADER_SIZE)
            self._write_header_sector(off // SECTOR_SIZE * SECTOR_SIZE)
            # Tear the prepare's own header sector too: recover() must not
            # resurrect the slot from the prepare ring.
            praw = self.storage.read(
                Zone.wal_prepares, slot * self.msg_max, HEADER_SIZE
            )
            p = Header.from_bytes(praw[:HEADER_SIZE])
            if p.valid_checksum() and p.command == Command.prepare and p.op > op_max:
                self.storage.write(
                    Zone.wal_prepares, slot * self.msg_max, bytes(SECTOR_SIZE)
                )
            if getattr(self, "faulty", None):
                if self.faulty.get(slot, 0) > op_max:
                    del self.faulty[slot]

    def get_header(self, op: int) -> Header | None:
        """The op's header from the in-memory redundant-header mirror (valid
        for faulty slots too — that is the point of the redundant ring)."""
        slot = self.slot_for_op(op)
        h = Header.from_bytes(
            bytes(self._headers[slot * HEADER_SIZE : (slot + 1) * HEADER_SIZE])
        )
        if h.valid_checksum() and h.command == Command.prepare and h.op == op:
            return h
        return None

    # -- read path --

    def read_prepare(self, op: int) -> tuple[Header, bytes] | None:
        """The prepare for `op`, or None if the slot holds a different op or
        fails its checksums."""
        slot = self.slot_for_op(op)
        raw = self.storage.read(Zone.wal_prepares, slot * self.msg_max, self.msg_max)
        header = Header.from_bytes(raw[:HEADER_SIZE])
        if not header.valid_checksum() or header.op != op:
            return None
        if header.command != Command.prepare:
            return None
        body = raw[HEADER_SIZE : header.size]
        if not header.valid_checksum_body(body):
            return None
        return header, body

    # -- recovery --

    def recover(self) -> dict[int, Header]:
        """Scan both rings; return op -> header for every slot whose prepare
        is intact (the replayable set). Rebuilds the in-memory header mirror
        from BOTH rings, records faulty slots, and classifies each slot
        into the decision matrix (reference: src/vsr/journal.zig:374-535):

        - valid:       prepare intact, rings agree (or redundant torn — the
                       prepare's own header wins: torn_header)
        - faulty:      redundant header survives but the prepare body is
                       torn — the op is KNOWN, the body repairable from any
                       acker (`faulty` records it; normal-status WAL scrub
                       and view-change adoption refetch it)
        - wrap_stale:  BOTH rings valid but the redundant header carries a
                       NEWER op for the slot — the newer prepare's write
                       was lost/rolled back while the previous ring pass's
                       prepare survives underneath. The redundant header is
                       the later evidence (it is only ever written AFTER
                       its prepare landed), so the slot is FAULTY for the
                       newer op; trusting the stale prepare would advertise
                       a superseded op in DVCs and could false-nack an
                       acked one.
        - misdirected: a checksum-valid prepare whose op does not map to
                       this slot — the write landed in the wrong place
                       (reference classifies misdirected reads/writes);
                       never evidence, the true slot content is lost.
        - blank:       neither ring holds anything usable.

        `recover_stats` counts the classifications (simulator assertions
        + observability)."""
        out: dict[int, Header] = {}
        self.faulty: dict[int, int] = {}  # slot -> op whose body is lost
        self.recover_stats = {
            "valid": 0, "torn_header": 0, "faulty": 0, "wrap_stale": 0,
            "misdirected": 0, "blank": 0,
        }
        raw_headers = self.storage.read(
            Zone.wal_headers, 0,
            (self.slot_count * HEADER_SIZE + SECTOR_SIZE - 1)
            // SECTOR_SIZE * SECTOR_SIZE,
        )
        for slot in range(self.slot_count):
            praw = self.storage.read(
                Zone.wal_prepares, slot * self.msg_max, self.msg_max
            )
            p_header = Header.from_bytes(praw[:HEADER_SIZE])
            p_valid = (
                p_header.valid_checksum()
                and p_header.command == Command.prepare
                and p_header.size <= self.msg_max
                and p_header.valid_checksum_body(
                    praw[HEADER_SIZE : p_header.size]
                )
            )
            p_here = p_valid and self.slot_for_op(p_header.op) == slot
            off = slot * HEADER_SIZE
            r_header = Header.from_bytes(raw_headers[off : off + HEADER_SIZE])
            r_ok = (
                r_header.valid_checksum()
                and r_header.command == Command.prepare
                and self.slot_for_op(r_header.op) == slot
            )
            if p_valid and not p_here:
                # misdirected write: the prepare belongs elsewhere; fall
                # back to the redundant ring for THIS slot's evidence
                self.recover_stats["misdirected"] += 1
                if r_ok:
                    self.faulty[slot] = r_header.op
                    self._headers[off : off + HEADER_SIZE] = (
                        r_header.to_bytes()
                    )
                continue
            if p_here and (not r_ok or r_header.op <= p_header.op):
                # the prepare is the newest evidence for the slot
                out[p_header.op] = p_header
                self._headers[off : off + HEADER_SIZE] = p_header.to_bytes()
                self.recover_stats[
                    "valid" if r_ok and r_header.op == p_header.op
                    else "torn_header"
                ] += 1
                continue
            if r_ok:
                # redundant header is the newest evidence; the body for its
                # op is lost (torn prepare, or a stale wrap underneath)
                self.faulty[slot] = r_header.op
                self._headers[off : off + HEADER_SIZE] = r_header.to_bytes()
                self.recover_stats[
                    "wrap_stale" if p_here else "faulty"
                ] += 1
                continue
            self.recover_stats["blank"] += 1
        self._headers_durable = bytearray(self._headers)
        return out
