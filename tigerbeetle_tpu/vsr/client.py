"""The native session client (reference: src/vsr/client.zig:17-80).

Protocol: register a session (an op committed through the cluster), then
one in-flight request at a time, each with a monotonically increasing
request number; the session number rides in `context` so the cluster can
evict stale sessions; replies are matched by request number. Retries resend
the SAME message bytes (idempotent via the replicated client table).

Fault-tolerant runtime (the reference's request/ping timeout state
machine, src/vsr/client.zig request_timeout/ping_timeout): the client is
TICK-driven through the same deterministic time seam the replica uses —
the simulator advances it with sim ticks, live drivers map wall clock
onto ticks with `WallTicker` — so every retry/backoff/failover decision
is reproducible under a seed and none of them needs driver code:

- request timeout: exponential backoff with deterministic jitter (the
  rng is seeded from the client id), resends RE-TARGETED round-robin
  across the replicas — after a primary crash the retry ladder walks the
  cluster until a replica in the new view answers, instead of hammering
  the dead primary forever;
- typed `busy` sheds back off on a DECORRELATED-jitter ladder distinct
  from the loss ladder (a shed is proof the replica is alive — the retry
  goes back to the same primary, and the loss timer re-arms rather than
  compounding);
- ping/pong view discovery while idle (`ping_client`/`pong_client`):
  an idle client learns a view change before its next request, so the
  first send targets the new primary;
- per-request deadlines surface a typed `RequestTimeout` from the wait
  path (poll/take_reply) instead of retrying forever;
- eviction surfaces a typed `SessionEvicted` from the wait path (the
  old behavior was a silent `evicted` flag and a request dropped on the
  floor), with opt-in automatic re-registration (`auto_reregister`) for
  fleets that should ride through client-table pressure.

Legacy drivers that never tick keep working: `resend()` and the
`reply`/`busy` fields behave exactly as before.
"""

from __future__ import annotations

import random

from tigerbeetle_tpu.io.network import Network
from tigerbeetle_tpu.metrics import NULL_METRICS
from tigerbeetle_tpu.types import Operation
from tigerbeetle_tpu.vsr.header import HEADER_SIZE, Command, Header


class ClientError(Exception):
    """Base of the typed client-runtime errors."""


class SessionEvicted(ClientError):
    """The cluster evicted this session from its client table (register
    pressure at clients_max, or a request carried a stale session). Any
    in-flight request's fate is UNKNOWN — it may or may not have
    committed before the eviction — so the runtime never silently
    retries it under a new session (that could execute it twice)."""

    def __init__(self, client_id: int, request: int | None):
        self.client_id = client_id
        self.request = request  # None: evicted while idle
        super().__init__(
            f"session evicted (client {client_id:#x}"
            + (f", request {request} in flight)" if request is not None
               else ", idle)")
        )


class RequestTimeout(ClientError):
    """The in-flight request exceeded its per-request deadline. The
    request is dropped (retries stop); like eviction, its fate is
    unknown — a caller that re-issues the same EVENTS under a new
    request number risks double execution, re-issuing the same request
    bytes is safe but the deadline already said it took too long."""

    def __init__(self, client_id: int, request: int, ticks: int):
        self.client_id = client_id
        self.request = request
        self.ticks = ticks
        super().__init__(
            f"request {request} deadline after {ticks} ticks "
            f"(client {client_id:#x})"
        )


class Timeout:
    """Tick-driven retry timer: exponential backoff with deterministic
    jitter (reference: src/vsr.zig Timeout.backoff/exponential_backoff_
    with_jitter). The duration is drawn ONCE per arm — base * 2^attempts
    plus up to 50% jitter from the client's seeded rng — so firing is a
    cheap integer compare and the same seed replays the same ladder."""

    __slots__ = ("after", "rng", "ticks", "attempts", "ticking",
                 "duration", "max_exponent")

    def __init__(self, after: int, rng: random.Random, max_exponent: int = 4):
        self.after = after
        self.rng = rng
        self.max_exponent = max_exponent
        self.ticks = 0
        self.attempts = 0
        self.ticking = False
        self.duration = after

    def _arm(self) -> None:
        base = self.after << min(self.attempts, self.max_exponent)
        self.duration = base + self.rng.randrange(base // 2 + 1)
        self.ticks = 0

    def start(self) -> None:
        self.ticking = True
        self.attempts = 0
        self._arm()

    def stop(self) -> None:
        self.ticking = False
        self.ticks = 0
        self.attempts = 0

    def rearm(self) -> None:
        """Restart the current attempt without climbing the ladder (a
        busy shed proved the path alive — the loss backoff must not
        compound on top of the busy backoff)."""
        if self.ticking:
            self._arm()

    def backoff(self) -> None:
        """After a fire: climb the ladder and re-arm."""
        self.attempts += 1
        self._arm()

    def tick(self) -> None:
        if self.ticking:
            self.ticks += 1

    def fired(self) -> bool:
        return self.ticking and self.ticks >= self.duration


class BusyBackoff:
    """Decorrelated-jitter backoff for typed busy sheds (next = min(cap,
    uniform(base, prev * 3)) — the AWS "decorrelated jitter" shape):
    sustained shed storms spread retries out instead of synchronizing
    them, and the ladder is DISTINCT from the loss timeout's exponential
    one (a shed is backpressure, not loss)."""

    __slots__ = ("rng", "base", "cap", "prev")

    def __init__(self, rng: random.Random, base: int = 2, cap: int = 64):
        self.rng = rng
        self.base = base
        self.cap = cap
        self.prev = 0

    def next_delay(self) -> int:
        hi = max(self.base + 1, self.prev * 3)
        self.prev = min(self.cap,
                        self.base + self.rng.randrange(hi - self.base + 1))
        return self.prev

    def reset(self) -> None:
        self.prev = 0


class Client:
    def __init__(self, client_id: int, network: Network, replica_count: int,
                 cluster_id: int = 0,
                 request_timeout_ticks: int = 30,
                 ping_ticks: int = 50,
                 deadline_ticks: int = 0,
                 auto_reregister: bool = False,
                 max_backoff_exponent: int = 4,
                 metrics=None):
        self.client_id = client_id
        self.network = network
        self.replica_count = replica_count
        self.cluster_id = cluster_id
        self.session = 0
        self.request_number = 0
        self.view = 0  # best-known view (updates from replies/pongs/busy)
        self.reply: tuple[Header, bytes] | None = None
        self.evicted = False
        self.in_flight: bytes | None = None
        # Load-shed signal (Command.busy from the ingress gateway): the
        # in-flight request was REFUSED, not lost — back off and resend.
        # The tick runtime consumes it itself; non-ticking drivers read
        # the flag and resend() after their own backoff, as before.
        self.busy = False
        self.busy_replies = 0
        # typed error surfaced by poll()/take_reply() (the wait path):
        # SessionEvicted or RequestTimeout
        self.error: ClientError | None = None
        # -- tick runtime state (all deterministic: the jitter rng is
        # seeded from the client id, time is injected ticks) --
        self.ticks = 0
        self.rng = random.Random(client_id ^ 0xC11E47)
        self.request_timeout = Timeout(
            request_timeout_ticks, self.rng,
            max_exponent=max_backoff_exponent,
        )
        self.busy_backoff = BusyBackoff(self.rng)
        self.ping_ticks = ping_ticks
        self.deadline_ticks = deadline_ticks
        self.auto_reregister = auto_reregister
        self._deadline_at = 0  # tick the in-flight request dies at (0: none)
        self._busy_at = 0  # tick the consumed busy shed resends at (0: none)
        self._retargets = 0  # timeout fires for THIS request (round-robin)
        self._inflight_op = 0  # operation byte of the in-flight request
        self._next_ping = 0  # idle-ping schedule (0: not scheduled)
        self._want_reregister = False
        m = metrics or NULL_METRICS
        self.metrics = m
        self._c_timeouts = m.counter("client.timeouts")
        self._c_resends = m.counter("client.resends")
        self._c_retargets = m.counter("client.retargets")
        self._c_busy = m.counter("client.busy_sheds")
        self._c_pings = m.counter("client.pings")
        self._c_pongs = m.counter("client.pongs")
        self._c_evictions = m.counter("client.evictions")
        self._c_reregisters = m.counter("client.reregisters")
        self._c_deadlines = m.counter("client.deadline_timeouts")
        self._c_stale = m.counter("client.stale_replies")
        network.attach(client_id, self._on_message)

    @property
    def primary_index(self) -> int:
        return self.view % self.replica_count

    def _on_message(self, src, data: bytes) -> None:
        header = Header.from_bytes(data[:HEADER_SIZE])
        if not header.valid_checksum():
            return
        body = data[HEADER_SIZE : header.size]
        if not header.valid_checksum_body(body):
            return
        if header.command == Command.eviction:
            self._on_eviction(header)
            return
        if header.command == Command.pong_client:
            # idle view discovery: the pong carries the replica's view, so
            # the next request targets the current primary
            self.view = max(self.view, header.view)
            self._c_pongs.add()
            return
        if header.command == Command.busy:
            # Strictly current-or-ignored: a busy is only meaningful for
            # the request that is IN FLIGHT right now, matched by request
            # number AND operation. Anything else (late busy for a taken
            # reply, a previous incarnation's register, a re-ordered
            # shed) is dropped with NO counter and NO flag — a stale shed
            # must not re-arm backoff against a request it never named.
            if (
                self.in_flight is None
                or header.request != self.request_number
                or header.operation != self._inflight_op
            ):
                return
            self.view = max(self.view, header.view)
            self.busy = True
            self.busy_replies += 1
            self._c_busy.add()
            # the shed proves the path is alive: the loss ladder restarts
            # (the busy ladder owns the retry; see tick())
            self.request_timeout.rearm()
            return
        if header.command != Command.reply:
            return
        if self.in_flight is None:
            # nothing awaiting: a duplicate of an already-taken reply.
            # Register replies in particular always carry request=0 and
            # request_number stays 0 after registration, so a late
            # duplicate (a shed-then-retried register racing the cached
            # resend) would otherwise be accepted and sit in `reply` to
            # be misread as the answer to the NEXT request.
            self._c_stale.add()
            return
        if header.request != self.request_number:
            self._c_stale.add()
            return  # stale reply
        self.view = max(self.view, header.view)
        self.in_flight = None
        self.busy = False
        self._busy_at = 0
        self._deadline_at = 0
        self._retargets = 0
        self.request_timeout.stop()
        self.busy_backoff.reset()
        self.reply = (header, body)

    def _on_eviction(self, header: Header) -> None:
        self.view = max(self.view, header.view)
        self.evicted = True
        self._c_evictions.add()
        inflight_request = (
            self.request_number if self.in_flight is not None else None
        )
        # the in-flight request's fate is unknown: never auto-retry it
        # under a new session (double-execution hazard) — surface it
        self.in_flight = None
        self.busy = False
        self._busy_at = 0
        self._deadline_at = 0
        self.request_timeout.stop()
        if inflight_request is not None or not self.auto_reregister:
            self.error = SessionEvicted(self.client_id, inflight_request)
        if self.auto_reregister:
            # the next tick re-registers (a fresh session; callers see
            # the error for the dropped request, then the session works).
            # session drops to 0 NOW: a driver gating on `session != 0`
            # must fall into its register-pending path instead of issuing
            # one more request under the dead session in the window
            # before the tick runs (the replica would evict it again).
            # Non-auto clients keep the stale value — legacy drivers
            # probe the dead session deliberately and read `evicted`.
            self.session = 0
            self._want_reregister = True

    # -- requests (the pump is external: network.run() / bus.pump()) --

    def register(self) -> None:
        assert self.session == 0 and self.in_flight is None
        self.request_number = 0
        h = Header(
            command=int(Command.request),
            operation=int(Operation.register),
            client=self.client_id,
            request=0,
            cluster=self.cluster_id,
        )
        self._send(h, b"")

    def request(self, operation: Operation, body: bytes) -> None:
        if self.error is not None:
            self.poll()  # unconsumed typed error: surface it, not assert
        assert self.session != 0, "register first"
        assert self.in_flight is None, "one in-flight request per client"
        self.request_number += 1
        h = Header(
            command=int(Command.request),
            operation=int(operation),
            client=self.client_id,
            context=self.session,
            request=self.request_number,
            cluster=self.cluster_id,
        )
        self._send(h, body)

    def _send(self, header: Header, body: bytes) -> None:
        header.set_checksum_body(body)
        header.set_checksum()
        self.in_flight = header.to_bytes() + body
        self._inflight_op = header.operation
        self.busy = False
        self._busy_at = 0
        self._retargets = 0
        self.request_timeout.start()
        self.busy_backoff.reset()
        self._deadline_at = (
            self.ticks + self.deadline_ticks if self.deadline_ticks else 0
        )
        self.network.send(self.client_id, self.primary_index, self.in_flight)

    def _reregister(self) -> None:
        """Post-eviction automatic re-registration: a fresh session under
        the same client id (the replicated table committed the eviction,
        so the register commits a brand-new entry)."""
        self._want_reregister = False
        self.session = 0
        self.evicted = False
        self._c_reregisters.add()
        self.register()

    def resend(self) -> None:
        """Retry the in-flight request. Broadcast to every replica: after a
        view change the client may not know the new primary yet; replicas
        that are not the primary ignore requests. Legacy seam for drivers
        that run their own retry clocks — the tick runtime uses the
        round-robin single-target resend instead (cheaper, and it walks
        the cluster deterministically)."""
        assert self.in_flight is not None
        self.busy = False
        self._busy_at = 0
        self.request_timeout.rearm()
        self._c_resends.add(self.replica_count)
        for r in range(self.replica_count):
            self.network.send(self.client_id, r, self.in_flight)

    # -- the tick-driven runtime --

    def tick(self) -> None:
        """One virtual-time step: fire timeouts, consume busy sheds,
        enforce deadlines, ping while idle. The simulator calls this once
        per sim tick; live drivers map wall time onto it (WallTicker)."""
        self.ticks += 1
        if self._want_reregister and self.in_flight is None:
            self._reregister()
            return
        if self.in_flight is None:
            if (
                self.ping_ticks
                and self.session
                and not self.evicted
                and self.error is None
            ):
                if self._next_ping == 0:
                    # first idle tick: schedule with a jittered phase so a
                    # fleet's pings spread instead of synchronizing
                    self._next_ping = (
                        self.ticks + self.rng.randrange(self.ping_ticks) + 1
                    )
                elif self.ticks >= self._next_ping:
                    self._next_ping = self.ticks + self.ping_ticks
                    self._send_ping()
            return
        self._next_ping = 0
        if self._deadline_at and self.ticks >= self._deadline_at:
            self._c_deadlines.add()
            self.error = RequestTimeout(
                self.client_id, self.request_number,
                self.ticks - (self._deadline_at - self.deadline_ticks),
            )
            self.in_flight = None
            self.busy = False
            self._busy_at = 0
            self._deadline_at = 0
            self.request_timeout.stop()
            return
        if self.busy and self._busy_at == 0:
            # consume the shed: schedule the resend on the busy ladder
            self._busy_at = self.ticks + self.busy_backoff.next_delay()
        if self._busy_at:
            if self.ticks >= self._busy_at:
                self._busy_at = 0
                self.busy = False
                self._c_resends.add()
                # a shed came FROM the primary (or named its view): retry
                # there, no retarget — the replica is alive, just loaded
                self.network.send(
                    self.client_id, self.primary_index, self.in_flight
                )
                self.request_timeout.rearm()
            return
        self.request_timeout.tick()
        if self.request_timeout.fired():
            self.request_timeout.backoff()
            self._c_timeouts.add()
            self._c_resends.add()
            # Round-robin re-target (reference: on_request_timeout sends
            # to view + attempts): fire k tries primary + k, so a dead
            # primary costs one fire before the retry walks the cluster
            # and finds a replica that answers (or forwards the view).
            self._retargets += 1
            dst = (self.primary_index + self._retargets) % self.replica_count
            if dst != self.primary_index:
                self._c_retargets.add()
            self.network.send(self.client_id, dst, self.in_flight)

    def _send_ping(self) -> None:
        """Idle view discovery: ping every replica; each normal replica
        answers pong_client stamped with its view (reference:
        src/vsr/client.zig on_ping_timeout pings the whole cluster)."""
        self._c_pings.add()
        h = Header(
            command=int(Command.ping_client),
            client=self.client_id,
            cluster=self.cluster_id,
        )
        h.set_checksum_body(b"")
        h.set_checksum()
        wire = h.to_bytes()
        for r in range(self.replica_count):
            self.network.send(self.client_id, r, wire)

    # -- the wait path --

    def poll(self) -> None:
        """Raise the pending typed error (SessionEvicted/RequestTimeout),
        if any — THE wait-path check: drivers spinning on `reply is None`
        call this each turn so a dead request surfaces instead of
        spinning forever. The error is consumed by raising."""
        if self.error is not None:
            err, self.error = self.error, None
            raise err

    @property
    def done(self) -> bool:
        """True when a reply is ready OR a typed error is pending (the
        wait loop's exit condition; take_reply/poll then resolves it)."""
        return self.reply is not None or self.error is not None

    def take_reply(self) -> tuple[Header, bytes]:
        if self.reply is None:
            self.poll()  # surface the typed error from the wait path
        assert self.reply is not None, "no reply pending"
        header, body = self.reply
        self.reply = None
        if header.operation == int(Operation.register):
            self.session = int.from_bytes(body[:8], "little")
        return header, body


class WallTicker:
    """Map wall time onto client ticks for LIVE drivers: advance(now)
    runs the tick runtime at tick_s cadence. The burst after a driver
    stall is BOUNDED so a paused process resumes with one retry, not a
    retry storm; the client itself never reads a clock (the seam stays
    deterministic — sim code drives tick() directly)."""

    __slots__ = ("client", "tick_s", "_last", "max_burst")

    def __init__(self, client: Client, tick_s: float = 0.01,
                 max_burst: int = 8):
        self.client = client
        self.tick_s = tick_s
        self._last = None
        self.max_burst = max_burst

    def advance(self, now: float) -> None:
        if self._last is None:
            self._last = now
            return
        n = int((now - self._last) / self.tick_s)
        if n <= 0:
            return
        self._last += n * self.tick_s
        for _ in range(min(n, self.max_burst)):
            self.client.tick()


# the counters every Client binds (pinned against the CATALOG by
# tests/test_metrics.py so the name set cannot drift)
CLIENT_METRIC_NAMES = (
    "client.timeouts", "client.resends", "client.retargets",
    "client.busy_sheds", "client.pings", "client.pongs",
    "client.evictions", "client.reregisters", "client.deadline_timeouts",
    "client.stale_replies",
)
