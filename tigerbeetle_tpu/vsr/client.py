"""The native session client (reference: src/vsr/client.zig:17-80).

Protocol: register a session (an op committed through the cluster), then
one in-flight request at a time, each with a monotonically increasing
request number; the session number rides in `context` so the cluster can
evict stale sessions; replies are matched by request number. Retries resend
the SAME message bytes (idempotent via the replicated client table)."""

from __future__ import annotations

from tigerbeetle_tpu.io.network import Network
from tigerbeetle_tpu.types import Operation
from tigerbeetle_tpu.vsr.header import HEADER_SIZE, Command, Header


class Client:
    def __init__(self, client_id: int, network: Network, replica_count: int,
                 cluster_id: int = 0):
        self.client_id = client_id
        self.network = network
        self.replica_count = replica_count
        self.cluster_id = cluster_id
        self.session = 0
        self.request_number = 0
        self.view = 0  # best-known view (updates from replies)
        self.reply: tuple[Header, bytes] | None = None
        self.evicted = False
        self.in_flight: bytes | None = None
        # Load-shed signal (Command.busy from the ingress gateway): the
        # in-flight request was REFUSED, not lost — the driver should back
        # off and resend() instead of waiting out the full retry timeout.
        self.busy = False
        self.busy_replies = 0
        network.attach(client_id, self._on_message)

    @property
    def primary_index(self) -> int:
        return self.view % self.replica_count

    def _on_message(self, src, data: bytes) -> None:
        header = Header.from_bytes(data[:HEADER_SIZE])
        if not header.valid_checksum():
            return
        body = data[HEADER_SIZE : header.size]
        if not header.valid_checksum_body(body):
            return
        if header.command == Command.eviction:
            self.evicted = True
            return
        if header.command == Command.busy:
            # the gateway shed the CURRENT request: keep it in flight so
            # resend() retries the same bytes after the driver's backoff
            if header.request == self.request_number and self.in_flight is not None:
                self.busy = True
                self.busy_replies += 1
            return
        if header.command != Command.reply:
            return
        if self.in_flight is None:
            # nothing awaiting: a duplicate of an already-taken reply.
            # Register replies in particular always carry request=0 and
            # request_number stays 0 after registration, so a late
            # duplicate (a shed-then-retried register racing the cached
            # resend) would otherwise be accepted and sit in `reply` to
            # be misread as the answer to the NEXT request.
            return
        if header.request != self.request_number:
            return  # stale reply
        self.view = max(self.view, header.view)
        self.in_flight = None
        self.busy = False
        self.reply = (header, body)

    # -- requests (the pump is external: network.run()) --

    def register(self) -> None:
        assert self.session == 0 and self.in_flight is None
        self.request_number = 0
        h = Header(
            command=int(Command.request),
            operation=int(Operation.register),
            client=self.client_id,
            request=0,
            cluster=self.cluster_id,
        )
        self._send(h, b"")

    def request(self, operation: Operation, body: bytes) -> None:
        assert self.session != 0, "register first"
        assert self.in_flight is None, "one in-flight request per client"
        self.request_number += 1
        h = Header(
            command=int(Command.request),
            operation=int(operation),
            client=self.client_id,
            context=self.session,
            request=self.request_number,
            cluster=self.cluster_id,
        )
        self._send(h, body)

    def _send(self, header: Header, body: bytes) -> None:
        header.set_checksum_body(body)
        header.set_checksum()
        self.in_flight = header.to_bytes() + body
        self.network.send(self.client_id, self.primary_index, self.in_flight)

    def resend(self) -> None:
        """Retry the in-flight request. Broadcast to every replica: after a
        view change the client may not know the new primary yet; replicas
        that are not the primary ignore requests (the reference's client
        learns the view from pings — command=ping_client — and resends to
        the primary; broadcasting is the transport-equivalent simplification
        until client pings land)."""
        assert self.in_flight is not None
        self.busy = False
        for r in range(self.replica_count):
            self.network.send(self.client_id, r, self.in_flight)

    def take_reply(self) -> tuple[Header, bytes]:
        assert self.reply is not None, "no reply pending"
        header, body = self.reply
        self.reply = None
        if header.operation == int(Operation.register):
            self.session = int.from_bytes(body[:8], "little")
        return header, body
