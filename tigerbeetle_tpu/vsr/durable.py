"""Single-replica durability: WAL-before-commit + checkpointed device state.

The reference's two-level durability (SURVEY.md §5.4; reference:
src/vsr/journal.zig WAL, src/vsr/replica.zig:3489-3561 checkpoint chain):

1. Every prepare is durable in the WAL BEFORE the state machine executes it.
2. Every `checkpoint_interval` ops, the full ledger state is snapshotted:
   the HBM tables pull to host and write to the grid zone (ping-ponged by
   sequence parity), THEN the superblock durably records the new
   checkpoint op + blob references — state first, mark second, exactly the
   reference's ordering, so a crash between the two recovers from the
   PREVIOUS checkpoint + WAL replay.

Recovery = superblock quorum open -> load snapshot blobs into device state
-> journal scan -> replay prepares (checkpoint_op, head] through the same
kernels. Replay is deterministic: the hazard tracker's admission state is
part of the snapshot, so tier selection repeats identically.

This is the durability seam the VSR replica builds on; with replica_count=1
it IS the `format`/`start` lifecycle of the process (reference:
src/tigerbeetle/main.zig:54-60).

NOTE on the tunneled-TPU environment: snapshotting pulls the HBM tables to
host (d2h), which is slow over the session's tunnel — production tables
checkpoint fine on locally-attached TPUs; tests use TEST_PROCESS-sized
tables.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from tigerbeetle_tpu import native
from tigerbeetle_tpu.constants import (
    ConfigCluster,
    ConfigProcess,
    DEFAULT_CLUSTER,
    DEFAULT_PROCESS,
)
from tigerbeetle_tpu.io.storage import Storage, Zone
from tigerbeetle_tpu.models.ledger import DeviceLedger, init_state
from tigerbeetle_tpu.state_machine import StateMachine
from tigerbeetle_tpu.types import Operation
from tigerbeetle_tpu.vsr.header import Command, Header
from tigerbeetle_tpu.vsr.journal import Journal
from tigerbeetle_tpu.vsr.superblock import BlobRef, SuperBlock, VSRState

SNAPSHOT_LEAVES = ("acct_rows", "xfer_rows", "fulfill")
# Checkpoint blobs that are replica HOST state, not ledger state: they
# ride the same grid area / sync-shipping machinery but the ledger
# restore skips them (the replica reads its own back by name). Today:
# the many-session client table (ingress mode), which at 10k+ sessions
# overflows the 64 KiB superblock copy it used to inline into.
HOST_BLOBS = frozenset({"client_table"})
COUNTER_LEAVES = (
    "commit_ts", "acct_count", "xfer_count",
    "acct_used_slots", "xfer_used_slots",
)


def format_data_file(storage: Storage, cluster: ConfigCluster = DEFAULT_CLUSTER,
                     cluster_id: int = 0, replica: int = 0) -> None:
    """Create a fresh data file: superblock sequence 1, empty WAL
    (reference: src/vsr/replica_format.zig)."""
    sb = SuperBlock(storage)
    sb.checkpoint(VSRState(
        cluster=cluster_id, replica=replica, sequence=1,
        meta={"config_fingerprint": str(cluster.fingerprint())},
    ))


def check_config_fingerprint(state, cluster: ConfigCluster) -> None:
    """Mixed-config guard (reference: src/config.zig:167-179): refuse to
    open a data file formatted with different consensus-affecting
    constants."""
    want = state.meta.get("config_fingerprint")
    if want is not None and int(want) != cluster.fingerprint():
        raise RuntimeError(
            "data file was formatted with a different cluster config "
            "(consensus-affecting constants differ) — refusing to start"
        )


def snapshot_to_superblock(
    storage: Storage,
    ledger: DeviceLedger,
    sm: StateMachine,
    superblock: SuperBlock,
    commit_min: int,
    commit_min_checksum: int,
    extra_meta: dict | None = None,
    extra_blobs: list[tuple[str, bytes]] | None = None,
) -> None:
    """Checkpoint the ledger state: blobs to the grid zone (ping-ponged by
    sequence parity), THEN the superblock records them — state first, mark
    second (reference: src/vsr/replica.zig:3489-3561 ordering). Shared by
    the single-replica DurableLedger and the VSR Replica."""
    state = superblock.state
    assert state is not None
    sequence = state.sequence + 1
    # Explicit ping-pong: blobs go to the OTHER area than the live
    # checkpoint's (sequence numbers may advance without blob writes — view
    # persistence — so parity alone would not alternate correctly).
    area = 1 - state.area
    area_size = storage.layout.snapshot_area_size
    base = area * area_size

    carry = {  # format-time identity survives every checkpoint
        k: state.meta[k]
        for k in ("config_fingerprint",)
        if k in state.meta
    }
    blobs: list[BlobRef] = []
    off = base
    # backend seam: device ledger snapshots its HBM leaves as blobs; any
    # backend with snapshot_bytes (oracle, native engine, sharded mesh
    # ledger) snapshots one opaque blob
    if hasattr(ledger, "state") and not hasattr(ledger, "snapshot_bytes"):
        dev = ledger.state
        for name in SNAPSHOT_LEAVES:
            data = np.asarray(dev[name]).tobytes()
            assert off + len(data) <= base + area_size, "grid area overflow"
            storage.write(Zone.grid, off, data)
            blobs.append(BlobRef(name, off, len(data), native.checksum(data)))
            off += (len(data) + 4095) // 4096 * 4096
        h = ledger.hazards
        meta = {
            "counters": {k: int(np.asarray(dev[k])) for k in COUNTER_LEAVES},
            "fault": int(np.asarray(dev["fault"])),
            "acct_used": ledger._acct_used,
            "xfer_used": ledger._xfer_used,
            "amount_sum": str(h.amount_sum),  # may exceed u64: JSON as str
            "limit_account_ids": [str(x) for x in sorted(h.limit_account_ids)],
            **carry,
            **(extra_meta or {}),
        }
        if getattr(ledger, "spill", None) is not None:
            # flush the LSM backing store and record its manifest + the
            # spilled-id set (models/spill.py checkpoint contract); the
            # forest's grid blocks are durable before storage.sync() below
            meta["spill"] = ledger.spill.checkpoint_meta()
        assert meta["fault"] == 0, "refusing to checkpoint a faulted ledger"
    else:  # oracle / native / sharded backend: one opaque blob
        data = ledger.snapshot_bytes()
        assert off + len(data) <= base + area_size, "grid area overflow"
        storage.write(Zone.grid, off, data)
        blobs.append(BlobRef("oracle", off, len(data), native.checksum(data)))
        off += (len(data) + 4095) // 4096 * 4096
        meta = {"fault": 0, **carry, **(extra_meta or {})}
    # host-state blobs (e.g. a many-session client table too large for
    # the 64 KiB superblock copy): same area, same checksum discipline;
    # restore_from_snapshot skips them (HOST_BLOBS) — the replica reads
    # its own back via the superblock's refs
    for name, data in extra_blobs or ():
        assert name in HOST_BLOBS, name
        assert off + len(data) <= base + area_size, "grid area overflow"
        storage.write(Zone.grid, off, data)
        blobs.append(BlobRef(name, off, len(data), native.checksum(data)))
        off += (len(data) + 4095) // 4096 * 4096
    storage.sync()  # blobs durable before the superblock points at them

    superblock.checkpoint(VSRState(
        cluster=state.cluster,
        replica=state.replica,
        sequence=sequence,
        commit_min=commit_min,
        commit_min_checksum=commit_min_checksum,
        commit_max=commit_min,
        prepare_timestamp=sm.prepare_timestamp,
        area=area,
        blobs=blobs,
        meta=meta,
    ))


def persist_view(superblock: SuperBlock, view: int, log_view: int) -> None:
    """Durably record view participation WITHOUT a state snapshot (blob refs
    carry over; the grid areas are untouched). VSR requires the view to be
    durable before voting/acking in it — otherwise a crash-restart could
    regress and form an intersecting quorum in an abandoned view."""
    state = superblock.state
    assert state is not None
    meta = dict(state.meta)
    meta["view"] = view
    meta["log_view"] = log_view
    superblock.checkpoint(
        dataclasses.replace(state, sequence=state.sequence + 1, meta=meta)
    )


def restore_from_snapshot(
    storage: Storage,
    ledger: DeviceLedger,
    sm: StateMachine,
    process: ConfigProcess,
    state: VSRState,
) -> None:
    """Load a checkpoint back into the ledger backend (inverse of
    snapshot_to_superblock; fresh state when the superblock has no blobs)."""
    if hasattr(ledger, "restore_bytes"):  # oracle/native/sharded backend
        for ref in state.blobs:
            if ref.name in HOST_BLOBS:
                continue  # replica host state, not ledger state
            if ref.name != "oracle":
                raise RuntimeError(
                    f"checkpoint blob {ref.name!r} was written by the DEVICE "
                    "backend; this replica is running the native/oracle "
                    "backend — restart with --backend device (or re-format)"
                )
            raw = storage.read(Zone.grid, ref.offset, ref.size)
            if native.checksum(raw) != ref.checksum:
                raise RuntimeError(f"snapshot blob {ref.name}: bad checksum")
            ledger.restore_bytes(raw)
        sm.prepare_timestamp = state.prepare_timestamp
        return

    import jax.numpy as jnp

    dev = init_state(process)
    if state.blobs:
        for ref in state.blobs:
            if ref.name in HOST_BLOBS:
                continue  # replica host state, not ledger state
            if ref.name == "oracle":
                raise RuntimeError(
                    "checkpoint blob was written by the native/oracle "
                    "backend; this replica is running the DEVICE backend — "
                    "restart with --backend native (or re-format)"
                )
            raw = storage.read(Zone.grid, ref.offset, ref.size)
            if native.checksum(raw) != ref.checksum:
                raise RuntimeError(f"snapshot blob {ref.name}: bad checksum")
            host = np.frombuffer(raw, dtype=np.uint32).reshape(
                np.asarray(dev[ref.name]).shape
            )
            dev[ref.name] = jnp.asarray(host)
        counters = state.meta["counters"]
        for k in COUNTER_LEAVES:
            # .get: checkpoints from before a counter existed restore as 0
            dev[k] = jnp.uint64(int(counters.get(k, 0)))
        ledger._acct_used = int(state.meta["acct_used"])
        ledger._xfer_used = int(state.meta["xfer_used"])
        h = ledger.hazards
        h.amount_sum = int(state.meta["amount_sum"])
        h.limit_account_ids = {int(x) for x in state.meta["limit_account_ids"]}
        h._limit_lo = np.sort(
            np.array(
                [int(x) & ((1 << 64) - 1) for x in state.meta["limit_account_ids"]],
                dtype=np.uint64,
            )
        )
        if "spill" in state.meta:
            if getattr(ledger, "spill", None) is None:
                raise RuntimeError(
                    "checkpoint has spilled LSM state but the ledger was "
                    "constructed without a forest: restoring would silently "
                    "lose every spilled transfer — pass forest= to "
                    "DeviceLedger"
                )
            ledger.spill.restore(state.meta["spill"])
    ledger.state = dev
    sm.prepare_timestamp = state.prepare_timestamp


class DurableLedger:
    """The durable single-replica process around the device ledger."""

    def __init__(
        self,
        storage: Storage,
        cluster: ConfigCluster = DEFAULT_CLUSTER,
        process: ConfigProcess = DEFAULT_PROCESS,
        mode: str = "auto",
    ):
        self.storage = storage
        self.cluster = cluster
        self.process = process
        # With a forest block area in the layout, the ledger spills its
        # cold transfer tail to an LSM forest in the grid zone's tail
        # (models/spill.py); checkpoints then persist the forest manifest
        # + spilled-id set in the superblock meta.
        self.forest = None
        if storage.layout.forest_blocks:
            from tigerbeetle_tpu.lsm.grid import Grid
            from tigerbeetle_tpu.lsm.groove import Forest

            self.forest = Forest(Grid(
                storage,
                offset=storage.layout.forest_offset,
                block_count=storage.layout.forest_blocks,
            ), memtable_max=getattr(process, "lsm_memtable_max", 2048))
        self.ledger = DeviceLedger(cluster, process, mode=mode,
                                   forest=self.forest)
        self.sm = StateMachine(self.ledger, cluster)
        self.journal = Journal(storage, cluster)
        self.superblock = SuperBlock(storage)
        self.op = 0  # latest prepared+committed op (single replica: equal)
        self.parent_checksum = 0  # prepare hash chain
        self.checkpoint_op = 0

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def open(self) -> None:
        """Superblock quorum -> snapshot restore -> WAL replay."""
        state = self.superblock.open()
        check_config_fingerprint(state, self.cluster)
        self._restore_snapshot(state)
        self.checkpoint_op = state.commit_min
        self.op = state.commit_min
        self.parent_checksum = state.commit_min_checksum
        # Replay the WAL tail in op order through the same kernels.
        recovered = self.journal.recover()
        op = state.commit_min + 1
        while op in recovered:
            header, body = self.journal.read_prepare(op)  # type: ignore
            assert header.parent == self.parent_checksum, (
                f"hash chain break at op {op}"
            )
            operation = Operation(header.operation)
            self.sm.prepare(operation, body)
            assert self.sm.prepare_timestamp == header.timestamp, (
                "replay timestamp drift"
            )
            self.sm.commit(operation, header.timestamp, body)
            self.parent_checksum = header.checksum
            self.op = op
            op += 1

    # ------------------------------------------------------------------
    # the request path (reference: WAL-before-commit invariant)
    # ------------------------------------------------------------------

    def submit(self, operation: Operation, body: bytes) -> bytes:
        """Durably log, then execute; returns the wire reply body."""
        if operation in (Operation.create_accounts, Operation.create_transfers):
            op = self.op + 1
            # WAL wrap guard: never overwrite an un-checkpointed slot
            # (reference: src/vsr.zig:2003-2035 keeps a bar of headroom).
            if op - self.checkpoint_op >= self.cluster.checkpoint_interval:
                self.checkpoint()
            self.sm.prepare(operation, body)
            header = Header(
                parent=self.parent_checksum,
                cluster=self.superblock.state.cluster if self.superblock.state else 0,
                op=op,
                commit=self.op,
                timestamp=self.sm.prepare_timestamp,
                command=int(Command.prepare),
                operation=int(operation),
            )
            header.set_checksum_body(body)
            header.set_checksum()
            self.journal.write_prepare(header, body)  # durable BEFORE commit
            reply = self.sm.commit(operation, header.timestamp, body)
            self.parent_checksum = header.checksum
            self.op = op
            return reply
        # Lookups don't prepare (read-only; reference: lookups still go
        # through consensus for linearizability — the replica layer does
        # that; single-replica reads are trivially linearizable).
        return self.sm.commit(operation, self.sm.prepare_timestamp, body)

    # ------------------------------------------------------------------
    # checkpoint (state first, superblock second)
    # ------------------------------------------------------------------

    def checkpoint(self) -> None:
        snapshot_to_superblock(
            self.storage, self.ledger, self.sm, self.superblock,
            commit_min=self.op, commit_min_checksum=self.parent_checksum,
        )
        self.checkpoint_op = self.op

    def _restore_snapshot(self, state: VSRState) -> None:
        restore_from_snapshot(
            self.storage, self.ledger, self.sm, self.process, state
        )
