"""The VSR replica: consensus-driven replication of the device ledger.

Viewstamped Replication normal path (reference: src/vsr/replica.zig —
on_request :1208, on_prepare :1262, on_prepare_ok :1346, on_commit :1485,
commit dispatch :3045-3103):

- The PRIMARY (view % replica_count) sequences client requests into
  prepares: assigns op + batch-final timestamp, hash-chains the header to
  its predecessor, journals it (WAL-before-ack), broadcasts to backups, and
  counts prepare_oks (its own journal write included).
- BACKUPS verify the chain, journal the prepare, and ack prepare_ok.
- At a replication quorum (majority), the primary commits in op order
  through the StateMachine (the TPU device ledger), replies to the client,
  and advances commit_max; backups commit from their journal when the
  commit number reaches them (piggybacked on prepares + commit heartbeats).
- Client sessions are part of the replicated state: `register` ops flow
  through the log and every replica's client table updates identically
  (reference: src/vsr/replica.zig:3758-3860), so duplicate requests are
  answered from the table without re-execution.

View changes / repair / state sync land on top of this (reference
:1595-1924); status tracks it. All transport is real wire bytes through
the Network seam; all persistence through the Storage seam — so the
deterministic cluster (testing/cluster.py) runs this exact code.
"""

from __future__ import annotations

from tigerbeetle_tpu.constants import ConfigCluster, ConfigProcess
from tigerbeetle_tpu.io.network import Network
from tigerbeetle_tpu.io.storage import Storage
from tigerbeetle_tpu.io.time import Time
from tigerbeetle_tpu.models.ledger import DeviceLedger
from tigerbeetle_tpu.state_machine import StateMachine
from tigerbeetle_tpu.types import Operation
from tigerbeetle_tpu.vsr.durable import (
    restore_from_snapshot,
    snapshot_to_superblock,
)
from tigerbeetle_tpu.vsr.header import HEADER_SIZE, Command, Header
from tigerbeetle_tpu.vsr.journal import Journal
from tigerbeetle_tpu.vsr.superblock import SuperBlock


class Replica:
    def __init__(
        self,
        replica_index: int,
        replica_count: int,
        storage: Storage,
        network: Network,
        time: Time,
        cluster: ConfigCluster,
        process: ConfigProcess,
        mode: str = "auto",
        backend_factory=None,
    ):
        self.replica = replica_index
        self.replica_count = replica_count
        self.network = network
        self.time = time
        self.cluster = cluster
        backend = (
            backend_factory()
            if backend_factory is not None
            else DeviceLedger(cluster, process, mode=mode)
        )
        self.ledger = backend
        self.sm = StateMachine(backend, cluster)
        self.journal = Journal(storage, cluster)
        self.superblock = SuperBlock(storage)
        self.storage = storage

        self.status = "recovering"
        self.view = 0
        self.op = 0  # highest prepared op
        self.commit_min = 0  # highest committed op
        self.commit_max = 0  # highest known-committed op cluster-wide
        self.parent_checksum = 0  # checksum of prepare `self.op`
        self.commit_checksum = 0  # checksum of prepare `self.commit_min`
        self.checkpoint_op = 0

        # primary state
        self.pipeline: dict[int, dict] = {}  # op -> {header, body, oks}
        # replicated session state: client_id -> {session, request, reply}
        self.client_table: dict[int, dict] = {}
        # backup reorder buffer for out-of-order prepares
        self._pending_prepares: dict[int, tuple[Header, bytes]] = {}

        network.attach(replica_index, self._on_message)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    @property
    def primary_index(self) -> int:
        return self.view % self.replica_count

    @property
    def is_primary(self) -> bool:
        return self.replica == self.primary_index and self.status == "normal"

    def open(self) -> None:
        """Superblock -> snapshot -> WAL replay (same recovery as the
        single-replica DurableLedger, then join the cluster)."""
        state = self.superblock.open()
        restore_from_snapshot(
            self.storage, self.ledger, self.sm, self.ledger.process, state
        )
        self.client_table = {
            int(c): dict(e, reply=None)
            for c, e in state.meta.get("client_table", {}).items()
        }
        self.checkpoint_op = state.commit_min
        self.commit_min = self.commit_max = self.op = state.commit_min
        self.parent_checksum = self.commit_checksum = state.commit_min_checksum
        recovered = self.journal.recover()
        op = state.commit_min + 1
        while op in recovered:
            header, body = self.journal.read_prepare(op)  # type: ignore
            assert header.parent == self.parent_checksum
            self._commit_prepare(header, body)
            self.op = op
            self.parent_checksum = self.commit_checksum = header.checksum
            self.commit_min = self.commit_max = op
            op += 1
        self.status = "normal"

    def checkpoint(self) -> None:
        """Durably snapshot the committed state AT commit_min (pipelined
        ops beyond it stay replayable in the WAL). The replicated client
        table rides in the snapshot meta — it is part of the replicated
        state (reference: src/vsr/superblock.zig ClientSessions trailer)."""
        table = {
            str(c): {"session": e["session"], "request": e["request"]}
            for c, e in self.client_table.items()
        }
        snapshot_to_superblock(
            self.storage, self.ledger, self.sm, self.superblock,
            commit_min=self.commit_min,
            commit_min_checksum=self.commit_checksum,
            extra_meta={"client_table": table},
        )
        self.checkpoint_op = self.commit_min

    def _maybe_checkpoint(self, next_op: int) -> None:
        """WAL-wrap guard: never let a prepare overwrite an op that is not
        covered by a checkpoint (reference: src/vsr.zig:2003-2035 keeps a
        bar of headroom)."""
        if next_op - self.checkpoint_op >= self.cluster.checkpoint_interval:
            self.checkpoint()  # snapshots at commit_min
        assert next_op - self.checkpoint_op < self.cluster.journal_slot_count, (
            "WAL would wrap uncommitted ops: pipeline stuck"
        )

    # ------------------------------------------------------------------
    # message dispatch
    # ------------------------------------------------------------------

    def _on_message(self, src, data: bytes) -> None:
        header = Header.from_bytes(data[:HEADER_SIZE])
        if not header.valid_checksum():
            return  # corrupt: drop (reference: message_bus checksum gate)
        body = data[HEADER_SIZE : header.size]
        if not header.valid_checksum_body(body):
            return
        if self.status != "normal":
            return
        cmd = Command(header.command)
        if cmd == Command.request:
            self._on_request(header, body)
        elif cmd == Command.prepare:
            self._on_prepare(header, body)
        elif cmd == Command.prepare_ok:
            self._on_prepare_ok(header)
        elif cmd == Command.commit:
            self._on_commit(header)

    def _send(self, dst, header: Header, body: bytes = b"") -> None:
        header.set_checksum_body(body)
        header.replica = self.replica
        header.view = self.view
        header.cluster = self.superblock.state.cluster if self.superblock.state else 0
        header.set_checksum()
        self.network.send(self.replica, dst, header.to_bytes() + body)

    def _broadcast(self, header: Header, body: bytes = b"") -> None:
        import dataclasses

        for r in range(self.replica_count):
            if r != self.replica:
                self._send(r, dataclasses.replace(header), body)

    # ------------------------------------------------------------------
    # primary: request -> prepare
    # ------------------------------------------------------------------

    @property
    def quorum_replication(self) -> int:
        return self.replica_count // 2 + 1

    def _on_request(self, header: Header, body: bytes) -> None:
        if not self.is_primary:
            return  # client retries against the right primary
        client = header.client
        entry = self.client_table.get(client)
        operation = Operation(header.operation)

        if operation != Operation.register:
            if entry is None or header.context != entry["session"]:
                self._send_eviction(client)
                return
            if header.request <= entry["request"]:
                if header.request == entry["request"] and entry["reply"] is not None:
                    self.network.send(self.replica, client, entry["reply"])
                return  # duplicate/stale: drop (reply resent above)
            # Retransmission of a request still awaiting quorum: already in
            # the pipeline — preparing it again would execute it twice
            # (reference: pipeline_prepare_queue message_by_client check).
            for entry_p in self.pipeline.values():
                h = entry_p["header"]
                if h.client == client and h.request == header.request:
                    return

        op = self.op + 1
        assert op not in self.pipeline
        self._maybe_checkpoint(op)
        if operation != Operation.register:
            self.sm.prepare(operation, body)
        prepare = Header(
            parent=self.parent_checksum,
            client=client,
            context=header.checksum,  # checksum of the client's request
            request=header.request,
            op=op,
            commit=self.commit_max,
            timestamp=(
                self.sm.prepare_timestamp
                if operation != Operation.register
                else self.time.realtime()
            ),
            command=int(Command.prepare),
            operation=int(operation),
            view=self.view,
            cluster=self.superblock.state.cluster if self.superblock.state else 0,
            replica=self.replica,
        )
        prepare.set_checksum_body(body)
        prepare.set_checksum()
        self.journal.write_prepare(prepare, body)
        self.op = op
        self.parent_checksum = prepare.checksum
        self.pipeline[op] = {"header": prepare, "body": body,
                             "oks": {self.replica}}
        self._broadcast_prepare(prepare, body)
        self._maybe_commit_pipeline()

    def _broadcast_prepare(self, prepare: Header, body: bytes) -> None:
        for r in range(self.replica_count):
            if r != self.replica:
                self.network.send(
                    self.replica, r, prepare.to_bytes() + body
                )

    def _send_eviction(self, client: int) -> None:
        h = Header(command=int(Command.eviction), client=client)
        self._send(client, h)

    # ------------------------------------------------------------------
    # backup: prepare -> prepare_ok
    # ------------------------------------------------------------------

    def _on_prepare(self, header: Header, body: bytes) -> None:
        if self.is_primary:
            return
        if header.op <= self.op:
            self._ack_prepare(header)  # duplicate: re-ack
            self._commit_up_to(header.commit)
            return
        if header.op > self.op + 1:
            self._pending_prepares[header.op] = (header, body)
            return
        if header.parent != self.parent_checksum:
            return  # chain break: needs repair (view-change layer)
        self._maybe_checkpoint(header.op)
        self.journal.write_prepare(header, body)
        self.op = header.op
        self.parent_checksum = header.checksum
        self._ack_prepare(header)
        self._commit_up_to(header.commit)
        # drain any buffered successors
        nxt = self._pending_prepares.pop(self.op + 1, None)
        if nxt is not None:
            self._on_prepare(*nxt)

    def _ack_prepare(self, prepare: Header) -> None:
        ok = Header(
            command=int(Command.prepare_ok),
            op=prepare.op,
            context=prepare.checksum,
            client=prepare.client,
            request=prepare.request,
            timestamp=prepare.timestamp,
            operation=prepare.operation,
        )
        self._send(self.primary_index, ok)

    # ------------------------------------------------------------------
    # commit
    # ------------------------------------------------------------------

    def _on_prepare_ok(self, header: Header) -> None:
        if not self.is_primary:
            return
        entry = self.pipeline.get(header.op)
        if entry is None or entry["header"].checksum != header.context:
            return
        entry["oks"].add(header.replica)
        self._maybe_commit_pipeline()

    def _maybe_commit_pipeline(self) -> None:
        committed = False
        while True:
            op = self.commit_min + 1
            entry = self.pipeline.get(op)
            if entry is None or len(entry["oks"]) < self.quorum_replication:
                break
            header, body = entry["header"], entry["body"]
            reply_body = self._commit_prepare(header, body)
            self.commit_min = self.commit_max = op
            self.commit_checksum = header.checksum
            del self.pipeline[op]
            self._reply(header, reply_body)
            committed = True
        if committed:
            # commit heartbeat so backups commit promptly (reference sends
            # these on a timeout; the scripted cluster has no timers yet)
            h = Header(command=int(Command.commit), commit=self.commit_max)
            self._broadcast(h)

    def _on_commit(self, header: Header) -> None:
        if self.is_primary:
            return
        self._commit_up_to(header.commit)

    def _commit_up_to(self, commit_max: int) -> None:
        self.commit_max = max(self.commit_max, commit_max)
        while self.commit_min < min(self.commit_max, self.op):
            op = self.commit_min + 1
            got = self.journal.read_prepare(op)
            assert got is not None, f"backup missing journaled op {op}"
            header, body = got
            self._commit_prepare(header, body)
            self.commit_min = op
            self.commit_checksum = header.checksum

    def _commit_prepare(self, header: Header, body: bytes) -> bytes:
        """Execute one prepare against the replicated state (identical on
        every replica — determinism is the consensus invariant)."""
        operation = Operation(header.operation)
        if operation == Operation.register:
            self.client_table[header.client] = {
                "session": header.op,
                "request": 0,
                "reply": None,
            }
            return header.op.to_bytes(8, "little")  # session number
        reply = self.sm.commit(operation, header.timestamp, body)
        self.sm.prepare_timestamp = max(self.sm.prepare_timestamp, header.timestamp)
        entry = self.client_table.get(header.client)
        if entry is not None:
            entry["request"] = header.request
        return reply

    def _reply(self, prepare: Header, reply_body: bytes) -> None:
        reply = Header(
            command=int(Command.reply),
            client=prepare.client,
            context=prepare.context,
            request=prepare.request,
            op=prepare.op,
            commit=prepare.op,
            timestamp=prepare.timestamp,
            operation=prepare.operation,
        )
        reply.set_checksum_body(reply_body)
        reply.replica = self.replica
        reply.view = self.view
        reply.set_checksum()
        wire = reply.to_bytes() + reply_body
        entry = self.client_table.get(prepare.client)
        if entry is not None:
            entry["reply"] = wire
        self.network.send(self.replica, prepare.client, wire)
